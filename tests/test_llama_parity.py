"""Numerical parity of the native dense model vs HF transformers (CPU, fp32).

This is the framework's ground-truth test: build a tiny random HF
LlamaForCausalLM / Qwen2 / Qwen3, pull its weights through the state-dict
adapter, and require logits to match torch within fp32 tolerance.
"""

import numpy as np
import pytest

from capabilities import skip_unless

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.config import BackendConfig, TransformerConfig
from automodel_tpu.models.llama import LlamaForCausalLM, LlamaStateDictAdapter


def _hf_tiny(model_type: str):
    import torch

    torch.manual_seed(0)
    if model_type == "llama":
        from transformers import LlamaConfig, LlamaForCausalLM as HFLlama

        cfg = LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
            rope_theta=10000.0, tie_word_embeddings=False,
        )
        return cfg, HFLlama(cfg).eval()
    if model_type == "qwen2":
        from transformers import Qwen2Config, Qwen2ForCausalLM

        cfg = Qwen2Config(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
            tie_word_embeddings=True,
        )
        return cfg, Qwen2ForCausalLM(cfg).eval()
    if model_type == "qwen3":
        from transformers import Qwen3Config, Qwen3ForCausalLM

        cfg = Qwen3Config(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            max_position_embeddings=256, tie_word_embeddings=False,
        )
        return cfg, Qwen3ForCausalLM(cfg).eval()
    raise ValueError(model_type)


@pytest.mark.parametrize("model_type", ["llama", "qwen2", "qwen3"])
def test_logits_parity_with_hf(model_type):
    import torch

    hf_cfg, hf_model = _hf_tiny(model_type)
    cfg = TransformerConfig.from_hf(hf_cfg)
    backend = BackendConfig(attn="sdpa", param_dtype="float32", compute_dtype="float32")
    model = LlamaForCausalLM(cfg, backend)

    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    # HF strips tied lm_head from the state dict; adapter never asks for it when tied.
    params = LlamaStateDictAdapter(cfg).from_hf(lambda k: sd[k])
    params = jax.tree.map(jnp.asarray, params)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, hf_cfg.vocab_size, size=(2, 17))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    out = np.asarray(model(params, jnp.asarray(ids)))
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-3)


def test_scan_matches_unrolled():
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=3,
        num_heads=4, num_kv_heads=4, head_dim=8,
    )
    m_scan = LlamaForCausalLM(cfg, BackendConfig(attn="sdpa", compute_dtype="float32"))
    m_loop = LlamaForCausalLM(
        cfg, BackendConfig(attn="sdpa", compute_dtype="float32", scan_layers=False)
    )
    params = m_scan.init(jax.random.key(0))
    ids = jnp.arange(12).reshape(1, 12) % 64
    np.testing.assert_allclose(
        np.asarray(m_scan(params, ids)), np.asarray(m_loop(params, ids)), atol=1e-5, rtol=1e-5
    )


def test_remat_matches_no_remat():
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8,
    )
    base = LlamaForCausalLM(cfg, BackendConfig(attn="sdpa", compute_dtype="float32"))
    remat = LlamaForCausalLM(
        cfg, BackendConfig(attn="sdpa", compute_dtype="float32", remat="full")
    )
    params = base.init(jax.random.key(1))
    ids = jnp.arange(16).reshape(2, 8) % 64

    def loss(m):
        def f(p):
            return m(p, ids).astype(jnp.float32).sum()
        return f

    g1 = jax.grad(loss(base))(params)
    g2 = jax.grad(loss(remat))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-3, rtol=1e-3
        ),
        g1,
        g2,
    )


def test_segment_ids_block_causal():
    """Packed sequences: tokens must not attend across segment boundaries."""
    from automodel_tpu.ops.attention import sdpa

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    seg = jnp.asarray([[0, 0, 0, 0, 1, 1, 1, 1]])
    out = sdpa(q, k, v, causal=True, segment_ids=seg)
    # second segment's first token attends only to itself → output == v there
    np.testing.assert_allclose(np.asarray(out[0, 4]), np.asarray(v[0, 4]), atol=1e-5)


def test_sliding_window_parity_with_hf():
    """Qwen2-style mixed full/windowed layers must match HF exactly in mask
    semantics (first max_window_layers layers attend fully)."""
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(0)
    hf_cfg = Qwen2Config(
        vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        use_sliding_window=True, sliding_window=4, max_window_layers=2,
        attn_implementation="eager",
    )
    hf = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = TransformerConfig.from_hf(hf_cfg)
    assert cfg.sliding_window == 4 and cfg.max_window_layers == 2
    model = LlamaForCausalLM(
        cfg, BackendConfig(attn="sdpa", param_dtype="float32", compute_dtype="float32")
    )
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = jax.tree.map(jnp.asarray, LlamaStateDictAdapter(cfg).from_hf(lambda k: sd[k]))
    ids = np.random.default_rng(0).integers(0, 96, size=(1, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    out = np.asarray(model(params, jnp.asarray(ids)))
    # masking errors produce O(0.1) diffs here (verified); 3e-3 is the
    # cpu-backend noise floor for this config
    np.testing.assert_allclose(out, ref, atol=3e-3)
    # wrong-window sanity: the match is not vacuous
    import dataclasses

    wrong = LlamaForCausalLM(
        dataclasses.replace(cfg, sliding_window=3),
        BackendConfig(attn="sdpa", param_dtype="float32", compute_dtype="float32"),
    )
    assert np.abs(np.asarray(wrong(params, jnp.asarray(ids))) - ref).max() > 0.01


def test_hf_roundtrip_to_hf():
    hf_cfg, hf_model = _hf_tiny("llama")
    cfg = TransformerConfig.from_hf(hf_cfg)
    adapter = LlamaStateDictAdapter(cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    params = adapter.from_hf(lambda k: sd[k])
    out_sd = dict(adapter.to_hf(params))
    for k in adapter.hf_keys():
        np.testing.assert_array_equal(out_sd[k], sd[k])


@skip_unless("partial_auto_shard_map")
def test_vocab_parallel_ce_matches_masked(devices8):
    """TP loss-parallel CE (reference TEParallelCrossEntropy) == plain CE."""
    from automodel_tpu.ops import losses as L
    from automodel_tpu.parallel.mesh import MeshConfig, build_mesh

    ctx = build_mesh(MeshConfig(dp_shard=4, tp=2), devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    hidden = jnp.asarray(rng.standard_normal((4, 8, 16)), jnp.float32)
    kernel = jnp.asarray(rng.standard_normal((16, 64)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 64, (4, 8)), jnp.int32)
    labels = labels.at[0, :3].set(-100)

    logits = hidden @ kernel
    ref_sum, ref_n = L.masked_cross_entropy(logits, labels)
    vp_sum, vp_n = L.vocab_parallel_cross_entropy(hidden, kernel, labels, ctx)
    assert int(vp_n) == int(ref_n)
    np.testing.assert_allclose(float(vp_sum), float(ref_sum), rtol=1e-5)

    # gradients agree too (the loss feeds training)
    g_ref = jax.grad(lambda h: L.masked_cross_entropy(h @ kernel, labels)[0])(hidden)
    g_vp = jax.grad(
        lambda h: L.vocab_parallel_cross_entropy(h, kernel, labels, ctx)[0]
    )(hidden)
    np.testing.assert_allclose(np.asarray(g_vp), np.asarray(g_ref), atol=1e-5)

    # e2e: train a tiny llama with loss_fn name=vocab_parallel_ce
    from automodel_tpu import auto_model
    from automodel_tpu.data.loader import place_batch
    from automodel_tpu.optim.builders import build_optimizer
    from automodel_tpu.training.train_state import TrainState
    from automodel_tpu.training.train_step import build_train_step, make_causal_lm_loss

    hf = {
        "architectures": ["LlamaForCausalLM"], "model_type": "llama",
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 2,
        "num_key_value_heads": 1, "head_dim": 16,
    }
    auto = auto_model.from_config(
        hf, ctx, {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32"},
        seed=0,
    )
    opt = build_optimizer(name="adamw", lr=2e-3, grad_clip_norm=1.0)
    state = TrainState.create(auto.params, jax.jit(opt.init)(auto.params))
    step = build_train_step(
        make_causal_lm_loss(auto.model, loss="vocab_parallel_ce", constrain=auto.constrain),
        opt,
    )
    ids = np.random.default_rng(1).integers(0, 64, size=(1, 8, 16)).astype(np.int32)
    batch = place_batch(ctx, {"input_ids": ids, "labels": ids})
    losses = []
    for _ in range(3):
        state, m = step(state, batch)
        losses.append(float(jax.device_get(m["loss"])))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
