"""Elastic-fleet tests (ISSUE 18): the autoscaler hysteresis state
machine, probe-sweep exponential backoff, the AKV1 ``weights_fetch`` /
``kv_push`` ops, peer warm-start with cold fallback, scale-down drain +
prefix migration, the closed router loop over in-process replicas, the
scale backends, and the report/fleet-status surfaces. Every chaos path
(peer dies mid-weights-stream, migration target dies mid-ship) is driven
in-process through the fault-injection knobs — tier-1. The two slow tests
at the bottom are the subprocess acceptance e2es (warm-vs-cold A/B and
the full scale-up → scale-down → migrate loop over real replica
processes)."""

import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from automodel_tpu.resilience import fault_injection as fi
from automodel_tpu.serving.engine import KVSpillConfig, WarmStartConfig
from automodel_tpu.serving.fleet.autoscale import (
    AutoscaleConfig,
    Autoscaler,
    FleetSignals,
    K8sFleetBackend,
    LocalProcessBackend,
    ScaleBackendError,
)
from automodel_tpu.serving.fleet.kv_transfer import (
    KVTransferError,
    KVTransferServer,
    fetch_weights,
    push_kv,
)
from automodel_tpu.serving.fleet.router import (
    FleetConfig,
    Router,
    probe_backoff_s,
)
from tests.test_fleet import _engine, _http_replica, _tiny_auto

# a valid AKV1 geometry for listeners that only serve weights (the
# weights op never touches the pool, but the header schema is shared)
_GEOM = {
    "layers": 2, "block_size": 4, "num_kv_heads": 2, "head_dim": 8,
    "kv_cache_dtype": "float32",
}


@pytest.fixture(autouse=True)
def _clear_injector():
    yield
    fi.activate(None)


def _close_front(server, loop):
    try:
        server.shutdown()
        server.server_close()
    except OSError:
        pass
    loop.close()


def _post_json(port, path, payload):
    """POST returning (status, body) — HTTP error statuses return
    normally (urllib raises on them; the retire tests need the body)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# probe backoff (satellite 1)
# ---------------------------------------------------------------------------


def test_probe_backoff_schedule():
    """Below the threshold every sweep probes (0.0); past it the delay
    doubles from base_s, jittered ±25%, capped at max_s — and never
    overflows at absurd failure counts."""
    for f in range(3):
        assert probe_backoff_s(f, after=3, base_s=2.0, max_s=30.0) == 0.0
    prev_raw = None
    for f in range(3, 12):
        raw = min(2.0 * 2 ** (f - 3), 30.0)
        delay = probe_backoff_s(f, after=3, base_s=2.0, max_s=30.0, salt="r0")
        assert 0.75 * raw - 1e-9 <= delay <= min(1.25 * raw, 30.0) + 1e-9
        if prev_raw is not None:
            assert raw >= prev_raw  # the raw schedule is monotone
        prev_raw = raw
    # deterministic per (salt, failures); different salts decorrelate the
    # fleet (that is the whole point of the jitter)
    assert probe_backoff_s(5, 3, 2.0, 30.0, "a") == probe_backoff_s(
        5, 3, 2.0, 30.0, "a"
    )
    assert any(
        probe_backoff_s(f, 3, 2.0, 30.0, "a")
        != probe_backoff_s(f, 3, 2.0, 30.0, "b")
        for f in range(3, 10)
    )
    assert probe_backoff_s(10_000, 3, 2.0, 30.0) <= 30.0


def test_probe_backoff_gates_router_sweeps_and_resets_on_success():
    """A dead replica is probed every sweep until probe_backoff_after
    failures, then skipped until its next_probe_t; forcing the clock past
    it probes again. (The instant reset on success is exercised by every
    fleet test that probes a live replica: consecutive_failures == 0.)"""
    router = Router(FleetConfig.from_dict({
        "replicas": [{"url": "http://127.0.0.1:9", "name": "dead"}],
        "block_size": 4, "probe_interval_s": 5.0,
        "probe_backoff_after": 3, "probe_backoff_max_s": 60.0,
    }))
    try:
        rep = router._replicas["dead"]
        for want in (1, 2, 3):
            router.probe_once()
            assert rep.consecutive_failures == want
        assert rep.next_probe_t is not None  # backed off
        due_at = rep.next_probe_t
        assert due_at > time.monotonic()  # in the future
        assert due_at < time.monotonic() + 5.0 * 1.25 + 1e-6
        router.probe_once()  # not due: skipped, failure count unchanged
        assert rep.consecutive_failures == 3
        assert rep.next_probe_t == due_at
        rep.next_probe_t = time.monotonic() - 1.0  # force due
        router.probe_once()
        assert rep.consecutive_failures == 4
    finally:
        router.close()


# ---------------------------------------------------------------------------
# autoscaler state machine (tentpole, pure)
# ---------------------------------------------------------------------------


def _asc(**over):
    d = {
        "enabled": True, "min_replicas": 1, "max_replicas": 4,
        "scale_up_consecutive": 2, "scale_down_consecutive": 3,
        "cooldown_s": 100.0, "window_s": 10.0,
    }
    d.update(over)
    return Autoscaler(AutoscaleConfig.from_dict(d))


_OVER = FleetSignals(ready_replicas=2, queue_depth=50.0)
_IDLE = FleetSignals(
    ready_replicas=2, queue_depth=0.0, shed_rate=0.0, occupancy=0.0
)
_MID = FleetSignals(
    ready_replicas=2, queue_depth=1.0, shed_rate=0.0, occupancy=0.5
)


def test_autoscaler_disabled_never_scales():
    a = _asc(enabled=False)
    for t in range(10):
        assert a.decide(_OVER, 1, float(t)) == (None, None)


def test_autoscaler_classify_triggers():
    a = _asc()
    base = dict(ready_replicas=2, queue_depth=1.0, shed_rate=0.0,
                occupancy=0.5)
    assert a.classify(FleetSignals(**{**base, "queue_depth": 50.0})) == (
        "over", "queue_depth")
    assert a.classify(FleetSignals(**{**base, "shed_rate": 2.0})) == (
        "over", "shed_rate")
    assert a.classify(FleetSignals(**{**base, "occupancy": 0.99})) == (
        "over", "occupancy")
    assert a.classify(FleetSignals(**base, slos_firing=1)) == (
        "over", "slo_firing")
    assert a.classify(FleetSignals(**base)) == ("hold", None)
    assert a.classify(_IDLE) == ("under", "idle")
    # unknown signals neither trigger nor count as quiet
    assert a.classify(FleetSignals(ready_replicas=2)) == ("hold", None)
    assert a.classify(FleetSignals(
        ready_replicas=2, queue_depth=0.0, occupancy=0.0, shed_rate=None,
    )) == ("hold", None)
    # an all-down fleet is an availability incident, not a load signal
    assert a.classify(FleetSignals(ready_replicas=0, queue_depth=99.0)) == (
        "hold", None)
    # SLO firing can be opted out of the up-triggers
    a2 = _asc(slo_firing_scales_up=False)
    assert a2.classify(FleetSignals(**base, slos_firing=3)) == ("hold", None)


def test_autoscaler_debounce_cooldown_and_clamps():
    a = _asc()
    assert a.decide(_OVER, 2, 0.0) == (None, None)  # streak 1 of 2
    assert a.decide(_OVER, 2, 1.0) == ("up", "queue_depth")
    a.note_scaled({"direction": "up"}, 1.0)
    # cooldown defers action; streaks keep accumulating underneath
    assert a.decide(_OVER, 3, 2.0) == (None, None)
    assert a.decide(_OVER, 3, 50.0) == (None, None)
    assert a.decide(_OVER, 3, 102.0) == ("up", "queue_depth")
    # at the ceiling: keep shedding loudly, never exceed max
    assert a.decide(_OVER, 4, 103.0) == (None, None)
    # scale-down debounce + floor clamp
    b = _asc(cooldown_s=0.0)
    for t in range(2):
        assert b.decide(_IDLE, 2, float(t)) == (None, None)
    assert b.decide(_IDLE, 2, 2.0) == ("down", "idle")
    b.note_scaled({"direction": "down"}, 2.0)
    for t in range(3, 7):
        assert b.decide(_IDLE, 1, float(t)) == (None, None)  # at the floor
    assert b.events_total == {"up": 0, "down": 1}


def test_autoscaler_noisy_sweep_resets_streak():
    a = _asc()
    assert a.decide(_OVER, 2, 0.0) == (None, None)
    assert a.decide(_MID, 2, 1.0) == (None, None)  # noise: streak resets
    assert a.decide(_OVER, 2, 2.0) == (None, None)  # back to 1 of 2
    assert a.decide(_OVER, 2, 3.0) == ("up", "queue_depth")
    st = a.status()
    assert st["over_streak"] == 2 and st["scale_ups"] == 0


# ---------------------------------------------------------------------------
# AKV1 weights_fetch + peer warm-start (tentpole pillar a)
# ---------------------------------------------------------------------------


def _weights_handler(auto):
    from automodel_tpu.checkpoint.checkpointer import param_tree_signature
    from automodel_tpu.serving.server import _tree_path_name

    def handler():
        sig = param_tree_signature(auto.params)
        leaves = jax.tree_util.tree_flatten_with_path(auto.params)[0]
        return sig, [(_tree_path_name(p), leaf) for p, leaf in leaves]

    return handler


def test_weights_fetch_round_trip_and_refusal():
    from automodel_tpu.checkpoint.checkpointer import param_tree_signature

    auto = _tiny_auto(seed=0)
    srv = KVTransferServer(
        _GEOM, port=0, weights_handler=_weights_handler(auto)
    ).start()
    try:
        sig, arrays = fetch_weights(("127.0.0.1", srv.port), timeout_s=30.0)
        expected = param_tree_signature(auto.params)
        assert sig["digest"] == expected["digest"]
        leaves = jax.tree_util.tree_flatten_with_path(auto.params)[0]
        assert len(arrays) == len(leaves)
        from automodel_tpu.serving.server import _tree_path_name

        for path, leaf in leaves:
            got = arrays[_tree_path_name(path)]
            assert np.array_equal(got, np.asarray(leaf))
    finally:
        srv.close()
    # a listener with no weights handler refuses loudly
    srv2 = KVTransferServer(_GEOM, port=0).start()
    try:
        with pytest.raises(KVTransferError, match="no weights"):
            fetch_weights(("127.0.0.1", srv2.port), timeout_s=10.0)
    finally:
        srv2.close()


def test_weights_stream_abort_chaos_raises():
    """The chaos knob truncates the stream after N leaves — the fetching
    side must die with a transport error, not return a partial tree."""
    auto = _tiny_auto(seed=0)
    srv = KVTransferServer(
        _GEOM, port=0, weights_handler=_weights_handler(auto)
    ).start()
    try:
        fi.activate({"weights_stream_abort_after": 1})
        with pytest.raises(KVTransferError):
            fetch_weights(("127.0.0.1", srv.port), timeout_s=10.0)
    finally:
        srv.close()


def test_warm_start_params_success_and_cold_fallbacks():
    """seed-1 replica streams seed-0 weights (same architecture → same
    signature, different values → the swap is observable); every failure
    mode — dead peer, tampered signature, mid-stream death — returns
    False and leaves the cold-built params untouched."""
    from automodel_tpu.serving.server import _warm_start_params

    peer = _tiny_auto(seed=0)
    srv = KVTransferServer(
        _GEOM, port=0, weights_handler=_weights_handler(peer)
    ).start()
    try:
        auto = _tiny_auto(seed=1)
        peer_leaves = jax.tree_util.tree_leaves(peer.params)
        before = [np.asarray(x).copy() for x in
                  jax.tree_util.tree_leaves(auto.params)]
        assert any(
            not np.array_equal(b, np.asarray(p))
            for b, p in zip(before, peer_leaves)
        ), "seeds 0 and 1 must differ for this test to prove anything"
        ws = WarmStartConfig(
            peer_host="127.0.0.1", peer_port=srv.port, timeout_s=30.0
        )
        assert _warm_start_params(auto, ws) is True
        for mine, theirs in zip(
            jax.tree_util.tree_leaves(auto.params), peer_leaves
        ):
            assert np.array_equal(np.asarray(mine), np.asarray(theirs))

        # fallback 1: peer unreachable
        auto2 = _tiny_auto(seed=1)
        dead = WarmStartConfig(
            peer_host="127.0.0.1", peer_port=9, timeout_s=2.0
        )
        assert _warm_start_params(auto2, dead) is False
        for mine, b in zip(jax.tree_util.tree_leaves(auto2.params), before):
            assert np.array_equal(np.asarray(mine), b)

        # fallback 2: peer dies mid-stream (the chaos path the slow e2e
        # also covers across processes)
        fi.activate({"weights_stream_abort_after": 1})
        auto3 = _tiny_auto(seed=1)
        assert _warm_start_params(auto3, ws) is False
        for mine, b in zip(jax.tree_util.tree_leaves(auto3.params), before):
            assert np.array_equal(np.asarray(mine), b)
        fi.activate(None)
    finally:
        srv.close()

    # fallback 3: signature mismatch — the peer serves a different tree
    def tampered():
        sig, leaves = _weights_handler(peer)()
        return {**sig, "digest": "not-my-architecture"}, leaves

    srv2 = KVTransferServer(_GEOM, port=0, weights_handler=tampered).start()
    try:
        auto4 = _tiny_auto(seed=1)
        ws2 = WarmStartConfig(
            peer_host="127.0.0.1", peer_port=srv2.port, timeout_s=30.0
        )
        assert _warm_start_params(auto4, ws2) is False
    finally:
        srv2.close()


# ---------------------------------------------------------------------------
# AKV1 kv_push + prefix migration (tentpole pillar b)
# ---------------------------------------------------------------------------


def _spill_engine():
    return _engine(kv_spill=KVSpillConfig(enabled=True, max_host_mb=4.0))


def test_kv_push_migrates_prefix_and_preserves_hits():
    """Engine A's hot blocks pushed to engine B's spill tier: B replays
    the prompt with a full prefix hit and bit-identical greedy tokens —
    the token-weighted hit rate survives the migration."""
    from automodel_tpu.serving.server import stats_snapshot

    eng_a = _spill_engine()
    rec_a = []
    eng_a.on_record = rec_a.append
    prompt = list(range(1, 14))  # 3 full blocks, 12 matchable tokens
    eng_a.submit(prompt, max_new_tokens=6)
    eng_a.run()
    hashes, kv = eng_a.export_hot_blocks()
    assert len(hashes) == 3 and kv is not None

    eng_b = _spill_engine()
    target = KVTransferServer(
        eng_b.kv_geometry(), port=0,
        push_handler=eng_b.receive_migrated_blocks,
    ).start()
    try:
        accepted = push_kv(
            ("127.0.0.1", target.port), hashes, kv, eng_a.kv_geometry()
        )
        assert accepted == 3
        eng_b.pool.check_invariants()
        # a second identical push is a no-op (B already holds every block)
        assert push_kv(
            ("127.0.0.1", target.port), hashes, kv, eng_a.kv_geometry()
        ) == 0
        rec_b = []
        eng_b.on_record = rec_b.append
        eng_b.submit(prompt, max_new_tokens=6)
        eng_b.run()
        assert rec_b[-1]["tokens"] == rec_a[-1]["tokens"]
        alloc = stats_snapshot(eng_b)["allocator"]
        assert alloc["prefix_hit_tokens"] == 12
        # geometry mismatch refuses before any row lands
        bad = dict(eng_a.kv_geometry(), block_size=8)
        with pytest.raises(KVTransferError, match="geometry"):
            push_kv(("127.0.0.1", target.port), hashes, kv, bad)
        # chaos: the target "dies" before acking — the pusher sees a
        # transport error, never a silent partial success
        fi.activate({"kv_push_drop_ack": True})
        with pytest.raises(KVTransferError):
            push_kv(("127.0.0.1", target.port), hashes, kv,
                    eng_a.kv_geometry())
    finally:
        target.close()


def test_retire_sequence_outcomes_and_deadline():
    """The scale-down orchestration: drain → export → push → one outcome
    record. Skipped without a target, complete with one, failed (within
    the deadline, degrading to plain drain) when the target is dead or
    dies mid-ship."""
    from automodel_tpu.serving.server import retire_sequence

    eng = _spill_engine()
    records = []
    eng.on_record = records.append
    server, loop = _http_replica(eng)
    try:
        prompt = list(range(1, 14))
        code, _ = _post_json(
            server.server_address[1], "/generate",
            {"prompt_ids": prompt, "max_new_tokens": 6, "id": "seed"},
        )
        assert code == 200
        # no target → plain drain, migration_skipped
        assert retire_sequence(eng, loop, None, 5.0) == "migration_skipped"
        skipped = [r for r in records if r["event"] == "migration_skipped"]
        assert skipped and skipped[0]["migrated_blocks"] == 0

        eng_b = _spill_engine()
        target = KVTransferServer(
            eng_b.kv_geometry(), port=0,
            push_handler=eng_b.receive_migrated_blocks,
        ).start()
        try:
            out = retire_sequence(
                eng, loop, {"host": "127.0.0.1", "port": target.port}, 10.0
            )
            assert out == "migration_complete"
            done = [r for r in records if r["event"] == "migration_complete"]
            assert done[0]["migrated_blocks"] == 3
            assert done[0]["hot_blocks"] == 3
            assert 0 <= done[0]["retire_s"] < 10.0
        finally:
            target.close()
    finally:
        _close_front(server, loop)

    # failure paths get a fresh engine (the one above is drained)
    eng2 = _spill_engine()
    records2 = []
    eng2.on_record = records2.append
    server2, loop2 = _http_replica(eng2)
    try:
        code, _ = _post_json(
            server2.server_address[1], "/generate",
            {"prompt_ids": list(range(1, 14)), "max_new_tokens": 6,
             "id": "seed2"},
        )
        assert code == 200
        t0 = time.monotonic()
        out = retire_sequence(
            eng2, loop2, {"host": "127.0.0.1", "port": 9}, 5.0
        )
        assert out == "migration_failed"
        assert time.monotonic() - t0 < 5.0 + 2.0  # never past the deadline
        failed = [r for r in records2 if r["event"] == "migration_failed"]
        assert failed and "error" in failed[0]

        # chaos: target accepts the stream then dies before acking
        eng_c = _spill_engine()
        target = KVTransferServer(
            eng_c.kv_geometry(), port=0,
            push_handler=eng_c.receive_migrated_blocks,
        ).start()
        try:
            fi.activate({"kv_push_drop_ack": True})
            out = retire_sequence(
                eng2, loop2, {"host": "127.0.0.1", "port": target.port}, 5.0
            )
            assert out == "migration_failed"
        finally:
            target.close()
    finally:
        _close_front(server2, loop2)


def test_retire_endpoint_http():
    """POST /retire: 400 without a hook or with a malformed migrate body,
    200 + immediate return with one (the drain runs on its own thread)."""
    from automodel_tpu.serving.server import serve_http

    eng = _engine()
    server, loop = serve_http(eng, None, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        code, body = _post_json(
            server.server_address[1], "/retire", {"deadline_s": 5.0}
        )
        assert code == 400 and "retire hook" in body["error"]
    finally:
        _close_front(server, loop)

    eng2 = _engine()
    called = threading.Event()
    seen = {}

    def on_retire(migrate, deadline_s):
        seen.update({"migrate": migrate, "deadline_s": deadline_s})
        called.set()

    server2, loop2 = serve_http(eng2, None, port=0, on_retire=on_retire)
    threading.Thread(target=server2.serve_forever, daemon=True).start()
    try:
        port = server2.server_address[1]
        code, body = _post_json(
            port, "/retire", {"migrate": {"host": "h"}, "deadline_s": 5.0}
        )
        assert code == 400  # migrate must be null or {host, port}
        code, body = _post_json(
            port, "/retire",
            {"migrate": {"host": "127.0.0.1", "port": 1}, "deadline_s": 7.0},
        )
        assert code == 200 and body["draining"] and body["migrate"]
        assert called.wait(timeout=10)
        assert seen == {
            "migrate": {"host": "127.0.0.1", "port": 1}, "deadline_s": 7.0,
        }
    finally:
        _close_front(server2, loop2)


# ---------------------------------------------------------------------------
# the closed loop: router + backend over in-process replicas
# ---------------------------------------------------------------------------


def test_router_closed_loop_scale_up_backfill_and_scale_down():
    """Deterministic signal injection through the REAL control path:
    probe sweep → decide → LocalProcessBackend spawn/retire → registry +
    metrics + scale_event records + time_to_ready backfill. (The signal
    rollup itself is covered by _fleet_signals federation tests and
    test_fleet_health.)"""
    from automodel_tpu.serving.fleet.status import render_table

    engines = [_engine()]
    fronts = [_http_replica(engines[0])]
    spawned_fronts = []

    def spawn(warm_peer):
        eng = _engine()
        # the serve CLI front stamps boot_t before the model build; an
        # in-process replica must do it itself for note_ready to measure
        eng.boot_t = time.perf_counter()
        front = _http_replica(eng)
        spawned_fronts.append(front)
        engines.append(eng)
        name = f"auto{len(spawned_fronts)}"
        return name, f"http://127.0.0.1:{front[0].server_address[1]}"

    retired = []
    backend = LocalProcessBackend(
        spawn,
        retire=lambda name, url, migrate, dl: retired.append(
            (name, migrate, dl)
        ),
    )
    records = []
    router = Router(
        FleetConfig.from_dict({
            "replicas": [{
                "url": f"http://127.0.0.1:{fronts[0][0].server_address[1]}",
                "name": "r0",
            }],
            "block_size": 4, "probe_interval_s": 30.0,
        }),
        on_record=records.append,
        autoscale_config=AutoscaleConfig.from_dict({
            "enabled": True, "min_replicas": 1, "max_replicas": 2,
            "scale_up_consecutive": 1, "scale_down_consecutive": 2,
            "cooldown_s": 0.0, "window_s": 5.0,
        }),
        scale_backend=backend,
    )
    try:
        router.probe_once()
        assert len(router._replicas) == 1
        router._fleet_signals = lambda now: FleetSignals(
            ready_replicas=1, queue_depth=99.0
        )
        router.probe_once()
        assert len(router._replicas) == 2  # spawned + registered
        ups = [r for r in records if r.get("event") == "scale_event"]
        assert len(ups) == 1
        assert ups[0]["direction"] == "up"
        assert ups[0]["trigger"] == "queue_depth"
        assert ups[0]["replicas_before"] == 1
        assert ups[0]["replicas_after"] == 2
        # hold band: next sweep probes the new replica ready and backfills
        # the event with its measured time_to_ready_s + boot_source
        router._fleet_signals = lambda now: _MID
        router.probe_once()
        router.probe_once()
        last = router.autoscaler.last_event
        assert last["time_to_ready_s"] is not None
        assert last["boot_source"] == "cold_hf"
        stats = router.stats()
        assert stats["autoscale"]["scale_ups"] == 1
        rendered = router.metrics.registry.render()
        assert "automodel_route_autoscale_target_replicas 2" in rendered
        assert (
            'automodel_route_autoscale_events_total{direction="up"} 1'
            in rendered
        )
        # persistent idle → debounced scale-down through the backend's
        # retire; the registry shrinks back to the floor
        router._fleet_signals = lambda now: FleetSignals(
            ready_replicas=2, queue_depth=0.0, shed_rate=0.0, occupancy=0.0
        )
        router.probe_once()
        router.probe_once()
        assert len(router._replicas) == 1
        assert len(retired) == 1
        name, migrate, deadline = retired[0]
        assert migrate is None  # no peer advertises a KV listener here
        assert deadline == pytest.approx(30.0)
        downs = [
            r for r in records
            if r.get("event") == "scale_event" and r["direction"] == "down"
        ]
        assert len(downs) == 1 and downs[0]["trigger"] == "idle"
        # fleet-status renders the controller state (satellite 6)
        table = render_table(router.stats())
        assert "autoscale: 1 replicas (bounds 1..2), 1 up / 1 down" in table
        assert "last scale: down (trigger=idle) 2 -> 1 replicas" in table
    finally:
        router.close()
        for server, loop in fronts + spawned_fronts:
            _close_front(server, loop)


def test_router_backend_failure_keeps_streak_and_retries():
    """A backend that throws must NOT start the cooldown — the streak
    stays live and the very next sweep retries the scale."""
    engines = [_engine()]
    front = _http_replica(engines[0])
    spawned = []
    attempts = {"n": 0}

    def flaky_spawn(warm_peer):
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise RuntimeError("no capacity")
        eng = _engine()
        f = _http_replica(eng)
        spawned.append(f)
        engines.append(eng)
        return "auto1", f"http://127.0.0.1:{f[0].server_address[1]}"

    records = []
    router = Router(
        FleetConfig.from_dict({
            "replicas": [{
                "url": f"http://127.0.0.1:{front[0].server_address[1]}",
                "name": "r0",
            }],
            "block_size": 4, "probe_interval_s": 30.0,
        }),
        on_record=records.append,
        autoscale_config=AutoscaleConfig.from_dict({
            "enabled": True, "max_replicas": 2,
            "scale_up_consecutive": 1, "cooldown_s": 300.0,
        }),
        scale_backend=LocalProcessBackend(flaky_spawn),
    )
    try:
        router._fleet_signals = lambda now: FleetSignals(
            ready_replicas=1, queue_depth=99.0
        )
        router.probe_once()  # spawn raises → no event, no cooldown
        assert len(router._replicas) == 1
        assert not [r for r in records if r.get("event") == "scale_event"]
        assert router.autoscaler._over_streak >= 1
        router.probe_once()  # retry lands despite the long cooldown_s
        assert len(router._replicas) == 2
        assert attempts["n"] == 2
        assert [
            r for r in records if r.get("event") == "scale_event"
        ][0]["direction"] == "up"
    finally:
        router.close()
        for server, loop in [front] + spawned:
            _close_front(server, loop)


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------


def test_scale_fleet_role_argv_and_validation():
    import types

    from automodel_tpu.launcher.k8s import scale_fleet_role

    cfg = types.SimpleNamespace(name="myfleet")
    argv = scale_fleet_role(cfg, "decode", 3, apply=False)
    assert argv == [
        "kubectl", "scale", "statefulset", "myfleet-decode", "--replicas=3",
    ]
    with pytest.raises(ValueError):
        scale_fleet_role(cfg, "router", 1, apply=False)
    with pytest.raises(ValueError):
        scale_fleet_role(cfg, "mixed", -1, apply=False)


def test_k8s_backend_desired_bookkeeping(monkeypatch):
    import types

    import automodel_tpu.launcher.k8s as k8s_mod

    calls = []
    monkeypatch.setattr(
        k8s_mod, "scale_fleet_role",
        lambda cfg, role, n, apply=True: calls.append((role, n)),
    )
    cfg = types.SimpleNamespace(name="f", mixed=2)
    be = K8sFleetBackend(cfg, role="mixed")
    assert be.desired == 2 and be.registry_managed is False
    name, url = be.spawn(None)
    assert (name, url) == ("", "")  # membership arrives via DNS probe
    assert be.desired == 3 and calls[-1] == ("mixed", 3)
    be.retire("f-mixed-2", "http://x", None, 30.0)
    assert be.desired == 2 and calls[-1] == ("mixed", 2)

    # kubectl failure rolls the desired count back and surfaces loudly
    def boom(cfg, role, n, apply=True):
        raise RuntimeError("kubectl: connection refused")

    monkeypatch.setattr(k8s_mod, "scale_fleet_role", boom)
    with pytest.raises(ScaleBackendError):
        be.spawn(None)
    assert be.desired == 2
    with pytest.raises(ScaleBackendError):
        be.retire("f-mixed-1", "http://x", None, 30.0)
    assert be.desired == 2


# ---------------------------------------------------------------------------
# report + observability surfaces (satellites 2, 6)
# ---------------------------------------------------------------------------


def test_report_strict_accepts_and_summarizes_elastic_records(tmp_path):
    from automodel_tpu.telemetry.report import (
        lint_metrics_jsonl,
        summarize_metrics,
    )

    rows = [
        {"event": "replica_ready", "ts": 10.0, "boot_source": "cold_hf",
         "time_to_ready_s": 42.5},
        {"event": "replica_ready", "ts": 11.0,
         "boot_source": "peer_warm_start", "time_to_ready_s": 7.25},
        {"event": "scale_event", "ts": 20.0, "direction": "up",
         "trigger": "queue_depth", "replica": "auto1",
         "replicas_before": 1, "replicas_after": 2},
        {"event": "scale_event", "ts": 90.0, "direction": "down",
         "trigger": "idle", "replica": "r0",
         "replicas_before": 2, "replicas_after": 1},
        {"event": "migration_complete", "ts": 91.0, "migrated_blocks": 3,
         "hot_blocks": 3, "retire_s": 1.5},
        {"event": "migration_failed", "ts": 95.0, "migrated_blocks": 0,
         "hot_blocks": 2, "retire_s": 5.0, "error": "KVTransferError: x"},
    ]
    path = tmp_path / "metrics.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    records, problems = lint_metrics_jsonl(str(path))
    assert problems == []
    s = summarize_metrics(records)
    assert s["scale_ups"] == 1 and s["scale_downs"] == 1
    assert s["scale_events"] == [
        {"direction": "up", "trigger": "queue_depth",
         "replicas_before": 1, "replicas_after": 2},
        {"direction": "down", "trigger": "idle",
         "replicas_before": 2, "replicas_after": 1},
    ]
    assert s["replica_boots"]["cold_hf"]["count"] == 1
    assert s["replica_boots"]["peer_warm_start"][
        "time_to_ready_p50_s"] == pytest.approx(7.25)
    assert s["prefix_migrations"] == {
        "complete": 1, "failed": 1, "skipped": 0, "migrated_blocks": 3,
    }


def test_fleet_status_renders_autoscale_footer():
    from automodel_tpu.serving.fleet.status import render_table

    stats = {
        "replicas": {}, "replicas_ready": 0,
        "autoscale": {
            "enabled": True, "min_replicas": 1, "max_replicas": 4,
            "over_streak": 0, "under_streak": 0,
            "scale_ups": 2, "scale_downs": 1,
            "last_event": {
                "direction": "up", "trigger": "shed_rate",
                "replicas_before": 2, "replicas_after": 3,
                "time_to_ready_s": 12.339,
            },
        },
    }
    out = render_table(stats)
    assert "autoscale: 0 replicas (bounds 1..4), 2 up / 1 down events" in out
    assert (
        "last scale: up (trigger=shed_rate) 2 -> 3 replicas, "
        "time_to_ready=12.34s" in out
    )


# ---------------------------------------------------------------------------
# slow subprocess acceptance e2es
# ---------------------------------------------------------------------------


def _spawn_elastic_replica(tmp_path, idx, serving_extra=None, inject=None):
    from tests.test_serving_chaos import _WORKER, _clean_env

    cfg = {
        "seed": 0,
        "model": {
            "hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "model_type": "llama",
                "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2, "head_dim": 8,
                "max_position_embeddings": 128,
            },
            "backend": {"attn": "sdpa", "param_dtype": "float32",
                        "compute_dtype": "float32"},
        },
        "distributed": {"dp_shard": 1},
        "generation": {"max_new_tokens": 6, "greedy": True},
        "serving": {
            "slots": 2, "block_size": 4, "num_blocks": 32,
            "prefill_chunk": 4, "max_seq_len": 64,
            "http": {"port": 0},
            "watchdog": {"enabled": False},
            "kv_spill": {"enabled": True, "max_host_mb": 4.0},
            **(serving_extra or {}),
        },
    }
    cfg_path = tmp_path / f"elastic_replica{idx}.yaml"
    cfg_path.write_text(json.dumps(cfg))
    env = _clean_env()
    if inject:
        env[fi.ENV_VAR] = json.dumps(inject)
    return subprocess.Popen(
        [sys.executable, _WORKER, "serve", "-c", str(cfg_path)],
        stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env,
    )


def _kill_all(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)


@pytest.mark.slow  # three replica subprocess boots
def test_warm_start_faster_than_cold_with_identical_outputs(tmp_path):
    """Acceptance A/B: with an injected HF-load delay, a peer-warm-started
    replica reaches ready measurably faster than a cold one (the delay is
    on the cold path it skips), reports boot_source=peer_warm_start, and
    serves bit-identical greedy tokens."""
    from tests.test_fleet import _http_json_raw
    from tests.test_serving_chaos import _replica_port

    peer = _spawn_elastic_replica(tmp_path, 0)
    procs = [peer]
    try:
        port_peer = _replica_port(peer)
        kv_port = _http_json_raw(port_peer, "/stats")["kv_transfer_port"]
        assert kv_port
        delay = {"hf_load_delay_ms": 6000.0}
        warm = _spawn_elastic_replica(
            tmp_path, 1,
            serving_extra={"warm_start": {
                "peer_host": "127.0.0.1", "peer_port": int(kv_port),
                "timeout_s": 120.0,
            }},
            inject=delay,
        )
        cold = _spawn_elastic_replica(tmp_path, 2, inject=delay)
        procs += [warm, cold]
        port_warm = _replica_port(warm)
        port_cold = _replica_port(cold)
        s_warm = _http_json_raw(port_warm, "/stats")
        s_cold = _http_json_raw(port_cold, "/stats")
        assert s_warm["boot_source"] == "peer_warm_start"
        assert s_cold["boot_source"] == "cold_hf"
        assert s_warm["time_to_ready_s"] is not None
        assert s_cold["time_to_ready_s"] is not None
        # the warm replica skipped the injected 6s cold-load delay; leave
        # half of it as margin against CPU compile-time noise
        assert s_warm["time_to_ready_s"] < s_cold["time_to_ready_s"] - 3.0
        prompt = list(range(1, 14))
        body_w = _http_json_raw(
            port_warm, "/generate",
            {"prompt_ids": prompt, "max_new_tokens": 6, "id": "w"},
        )
        body_c = _http_json_raw(
            port_cold, "/generate",
            {"prompt_ids": prompt, "max_new_tokens": 6, "id": "c"},
        )
        assert body_w["tokens"] == body_c["tokens"]
        assert body_w["completion_reason"] == body_c["completion_reason"]
    finally:
        _kill_all(procs)


@pytest.mark.slow  # two replica subprocess boots, one spawned mid-test
def test_elastic_fleet_e2e_scale_up_migrate_down(tmp_path):
    """The full loop over real processes: overload → the router spawns a
    warm-started replica through LocalProcessBackend; idle → the victim
    drains, ships its hot prefix to the survivor over kv_push, and exits
    0; every request gets a terminal answer and the migrated prefix is
    hot on the survivor (full token-weighted hit, identical tokens)."""
    from tests.test_fleet import _http_json_raw
    from tests.test_serving_chaos import _replica_port

    first = _spawn_elastic_replica(tmp_path, 0)
    procs = {"r0": first}
    ports = {}

    def spawn(warm_peer):
        idx = 1 + len(ports)
        extra = {}
        if warm_peer is not None:
            extra["warm_start"] = {
                "peer_host": warm_peer["host"],
                "peer_port": int(warm_peer["port"]),
                "timeout_s": 120.0,
            }
        p = _spawn_elastic_replica(tmp_path, idx, serving_extra=extra)
        name = f"auto{idx}"
        procs[name] = p
        port = _replica_port(p)
        ports[name] = port
        return name, f"http://127.0.0.1:{port}"

    records = []
    router = None
    try:
        ports["r0"] = _replica_port(first)
        router = Router(
            FleetConfig.from_dict({
                "replicas": [
                    {"url": f"http://127.0.0.1:{ports['r0']}", "name": "r0"},
                ],
                "block_size": 4, "probe_interval_s": 30.0,
                "retry_budget": 2,
            }),
            on_record=records.append,
            autoscale_config=AutoscaleConfig.from_dict({
                "enabled": True, "min_replicas": 1, "max_replicas": 2,
                "scale_up_consecutive": 1, "scale_down_consecutive": 2,
                "cooldown_s": 0.0, "window_s": 5.0,
                "retire_deadline_s": 60.0,
            }),
            scale_backend=LocalProcessBackend(spawn),  # default /retire
        )
        router.probe_once()
        prompt = list(range(1, 14))
        code, body0 = router.handle_generate(
            {"prompt_ids": prompt, "max_new_tokens": 6, "id": "seed"}
        )
        assert code == 200
        # deterministic overload signal through the real control path
        router._fleet_signals = lambda now: FleetSignals(
            ready_replicas=1, queue_depth=99.0
        )
        router.probe_once()  # blocks on the spawn, replica warm-starts
        ups = [r for r in records if r.get("event") == "scale_event"]
        assert len(ups) == 1 and ups[0]["direction"] == "up"
        survivor = ups[0]["replica"]
        s_new = _http_json_raw(ports[survivor], "/stats")
        assert s_new["boot_source"] == "peer_warm_start"
        # hold band: probe the new replica ready, keep serving
        router._fleet_signals = lambda now: _MID
        router.probe_once()
        survivor_touched = False
        for i in range(3):
            code, b = router.handle_generate(
                {"prompt_ids": prompt, "max_new_tokens": 6, "id": f"m{i}"}
            )
            assert code == 200 and b["tokens"] == body0["tokens"]
            if b["route"]["replica"] != "r0":
                survivor_touched = True
        # idle → scale down. r0 (first registered, least loaded) drains,
        # migrates its hot prefix to the survivor, and exits cleanly.
        router._fleet_signals = lambda now: FleetSignals(
            ready_replicas=2, queue_depth=0.0, shed_rate=0.0, occupancy=0.0
        )
        router.probe_once()
        router.probe_once()
        downs = [
            r for r in records
            if r.get("event") == "scale_event" and r["direction"] == "down"
        ]
        assert len(downs) == 1 and downs[0]["replica"] == "r0"
        assert procs["r0"].wait(timeout=120) == 0
        router.probe_once()
        # zero lost requests: the fleet still answers, and the survivor —
        # which never computed this prefix — serves it from the migrated
        # blocks with a full hit and identical tokens
        code, body1 = router.handle_generate(
            {"prompt_ids": prompt, "max_new_tokens": 6, "id": "after"}
        )
        assert code == 200
        assert body1["tokens"] == body0["tokens"]
        assert body1["route"]["replica"] == survivor
        assert body1["prefix_hit_tokens"] == 12
        if not survivor_touched:
            # the survivor never served this prompt, so the full hit above
            # can only have come from the migrated rows in its spill tier
            alloc = _http_json_raw(ports[survivor], "/stats")["allocator"]
            assert alloc["spilled_blocks"] >= 3
    finally:
        if router is not None:
            router.close()
        _kill_all(list(procs.values()))
