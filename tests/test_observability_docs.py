"""docs/observability.md's key glossary must cover every metrics-JSONL key
the recipes emit — the test_perf_docs.py verbatim-guard pattern applied to
the glossary.

The linter's key lists (telemetry/report.py `_NUMERIC_KEYS` /
`_DURATION_KEYS`) are the canonical registry of emitted keys: every PR
that teaches a recipe a new JSONL key must add it there for `report
--strict` to accept it, so gating the glossary on the same lists means a
key can never ship linted-but-undocumented. The goodput segment taxonomy
and the attempt-envelope keys are pinned the same way.
"""

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# envelope / marker keys the recipes emit that are not numeric-linted
_EXTRA_KEYS = (
    "attempt_id",
    "restart_count",
    "completion_reason",
    "retriable",
    "trace_id",
    "span_id",
    "parent_id",
    "stage",
    "nonfinite",
    "val_loss",
    "steps_spanned",
)


def _doc():
    return open(os.path.join(REPO, "docs", "observability.md")).read()


def test_every_linted_jsonl_key_has_a_glossary_row():
    from automodel_tpu.telemetry.report import _DURATION_KEYS, _NUMERIC_KEYS

    doc = _doc()
    missing = sorted(
        k
        for k in set(_NUMERIC_KEYS) | set(_DURATION_KEYS) | set(_EXTRA_KEYS)
        if f"`{k}`" not in doc
    )
    assert not missing, (
        "docs/observability.md glossary is missing rows for these "
        f"metrics-JSONL keys (add a `key` row): {missing}"
    )


def test_goodput_segment_taxonomy_is_documented():
    from automodel_tpu.telemetry.goodput import SEGMENT_KINDS

    doc = _doc()
    missing = sorted(k for k in SEGMENT_KINDS if f"`{k}`" not in doc)
    assert not missing, (
        "docs/observability.md Goodput section is missing segment rows: "
        f"{missing}"
    )
    # the rollup-only residual is part of the taxonomy too
    assert "`unattributed`" in doc


def test_goodput_metrics_exporter_names_are_documented():
    doc = _doc()
    for name in (
        "automodel_train_goodput_fraction",
        "automodel_train_goodput_seconds",
        "automodel_train_ckpt_{save,restore,drain}_seconds",
    ):
        assert name in doc, f"/metrics glossary missing {name}"
