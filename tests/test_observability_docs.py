"""docs/observability.md's key glossary must cover every metrics-JSONL key
the recipes emit — the test_perf_docs.py verbatim-guard pattern applied to
the glossary.

The linter's key lists (telemetry/report.py `_NUMERIC_KEYS` /
`_DURATION_KEYS`) are the canonical registry of emitted keys: every PR
that teaches a recipe a new JSONL key must add it there for `report
--strict` to accept it, so gating the glossary on the same lists means a
key can never ship linted-but-undocumented. The goodput segment taxonomy
and the attempt-envelope keys are pinned the same way.
"""

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# envelope / marker keys the recipes emit that are not numeric-linted
_EXTRA_KEYS = (
    "attempt_id",
    "restart_count",
    "completion_reason",
    "retriable",
    "trace_id",
    "span_id",
    "parent_id",
    "stage",
    "nonfinite",
    "val_loss",
    "steps_spanned",
    # elastic fleet (serving/fleet/autoscale.py): replica boot provenance
    # + the scale_event envelope's string fields
    "boot_source",
    "direction",
    "trigger",
)


def _doc():
    return open(os.path.join(REPO, "docs", "observability.md")).read()


def test_every_linted_jsonl_key_has_a_glossary_row():
    from automodel_tpu.telemetry.report import _DURATION_KEYS, _NUMERIC_KEYS

    doc = _doc()
    missing = sorted(
        k
        for k in set(_NUMERIC_KEYS) | set(_DURATION_KEYS) | set(_EXTRA_KEYS)
        if f"`{k}`" not in doc
    )
    assert not missing, (
        "docs/observability.md glossary is missing rows for these "
        f"metrics-JSONL keys (add a `key` row): {missing}"
    )


def test_goodput_segment_taxonomy_is_documented():
    from automodel_tpu.telemetry.goodput import SEGMENT_KINDS

    doc = _doc()
    missing = sorted(k for k in SEGMENT_KINDS if f"`{k}`" not in doc)
    assert not missing, (
        "docs/observability.md Goodput section is missing segment rows: "
        f"{missing}"
    )
    # the rollup-only residual is part of the taxonomy too
    assert "`unattributed`" in doc


def test_goodput_metrics_exporter_names_are_documented():
    doc = _doc()
    for name in (
        "automodel_train_goodput_fraction",
        "automodel_train_goodput_seconds",
        "automodel_train_ckpt_{save,restore,drain}_seconds",
    ):
        assert name in doc, f"/metrics glossary missing {name}"


# -- every emittable /metrics name must have a glossary row -------------------
#
# The doc names metrics both literally (`automodel_serve_queue_depth`) and
# as brace patterns (`automodel_serve_block_{allocated,freed}_total`,
# possibly wrapped across lines mid-pattern) and with label selectors
# (`automodel_alerts_firing{slo}`). The matcher normalizes the doc once and
# reads every token BOTH ways — brace-expanded and selector-stripped — so a
# documented name is found regardless of notation. False positives from the
# wrong reading are harmless: the result is only probed for membership.


def _expand_braces(tok: str) -> list[str]:
    out = [tok]
    for _ in range(4):  # bounded: patterns nest at most once in practice
        nxt = []
        for t in out:
            if "{" not in t or "}" not in t:
                nxt.append(t)
                continue
            pre, rest = t.split("{", 1)
            body, _, post = rest.partition("}")
            for alt in body.split(","):
                nxt.append(pre + alt + post)
        if nxt == out:
            break
        out = nxt
    return out


def _documented_names(doc: str) -> set:
    import re

    # metric names live in code spans (the same convention the JSONL-key
    # guard requires); adjacent spans are merged first so a brace pattern
    # wrapped mid-span (`automodel_train_{step,` + `loss,...}`) reassembles
    merged = re.sub(r"`\s*`", "", doc)
    names = set()
    for span in re.findall(r"`([^`]+)`", merged):
        span = re.sub(r"\s+", "", span)
        for tok in re.findall(r"automodel_[a-zA-Z0-9_{},=.]+", span):
            candidates = list(_expand_braces(tok))
            candidates.append(re.sub(r"\{[^{}]*\}", "", tok))  # label sel.
            for cand in candidates:
                for piece in re.split(r"[.,]", cand):
                    if piece and "{" not in piece and "=" not in piece:
                        names.add(piece)
    return names


def _fleet_plane_registries():
    """→ (serving, train, router-with-slo) registries + the federation's
    self-metric render names — every family the repo can expose, built
    jax-free (no engine, no device runtime)."""
    from automodel_tpu.serving.fleet.router import RouterMetrics
    from automodel_tpu.telemetry.federation import Federation, parse_exposition
    from automodel_tpu.telemetry.prometheus import (
        ServingMetrics,
        TrainMetricsExporter,
    )
    from automodel_tpu.telemetry.slo import SLOConfig, SLOEngine

    serving = ServingMetrics().registry
    train = TrainMetricsExporter().registry
    router = RouterMetrics().registry
    # the SLO engine registers its alert families on the router registry
    SLOEngine(
        SLOConfig(objectives=[{
            "name": "doc_guard", "kind": "gauge",
            "metric": "automodel_serve_queue_depth", "max_value": 1.0,
        }]),
        Federation(),
        registry=router,
    )
    fed = parse_exposition(Federation().render_federated())
    fed_names = [
        m.name + ("_total" if m.kind == "counter" else "")
        for m in fed.values()
    ]
    return serving, train, router, fed_names


def test_every_metric_render_name_is_documented():
    doc = _doc()
    documented = _documented_names(doc)
    serving, train, router, fed_names = _fleet_plane_registries()
    required = set(fed_names)
    for reg in (serving, train, router):
        required.update(m.render_name for m in reg._metrics.values())
    missing = sorted(k for k in required if k not in documented)
    assert not missing, (
        "docs/observability.md /metrics glossary is missing these "
        f"emittable metric names: {missing}"
    )


def test_fleet_aggregate_derivation_is_documented():
    """Every replica family reappears on the router as a derived
    automodel_fleet_* aggregate (gauges also grow a _max companion). The
    doc must either name a derived family literally or document the base
    family + the derivation rule — the rule text is pinned here so it
    cannot silently vanish while the test keeps passing."""
    from automodel_tpu.telemetry.federation import fleet_name

    doc = _doc()
    assert "insert `fleet_` after `automodel_`" in doc, (
        "docs/observability.md no longer states the fleet-name derivation "
        "rule"
    )
    assert "_max` companion" in doc, (
        "docs/observability.md no longer states the gauge _max companion "
        "rule"
    )
    documented = _documented_names(doc)
    serving, _, _, _ = _fleet_plane_registries()
    missing = []
    for m in serving._metrics.values():
        fleet_family = fleet_name(m.name)
        derived = [fleet_family + ("_total" if m.kind == "counter" else "")]
        if m.kind == "gauge":
            derived.append(fleet_family + "_max")
        for name in derived:
            base = m.render_name
            if name not in documented and base not in documented:
                missing.append(name)
    assert not missing, (
        "fleet aggregates underivable from the doc (document the base "
        f"family or the derived name): {sorted(set(missing))}"
    )
