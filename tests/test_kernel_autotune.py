"""Kernel autotune registry (ops/autotune.py) + tools/kernel_bench.py.

The registry is pure file/dict plumbing — fast unit tests — plus one CPU
end-to-end run of the sweep driver in interpret mode (the acceptance gate:
`tools/kernel_bench.py` must run anywhere and produce a table both kernel
families load, a markdown report, and a JSONL that telemetry/report.py
--strict accepts).
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import pytest

from automodel_tpu.ops import autotune

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tables(monkeypatch):
    monkeypatch.delenv(autotune.ENV_TABLE, raising=False)
    autotune.clear_cache()
    yield
    autotune.clear_cache()


def test_committed_v5e_defaults_exist_and_validate():
    """The committed defaults must carry the v5e entries both tentpole
    kernels load: the fused-backward tiles for the bench fingerprint
    (D=I=1536 bf16) and the head_dim-64 attention shapes."""
    for key in (
        autotune.moe_bwd_gu_key(1536, 1536, jnp.bfloat16),
        autotune.moe_bwd_dwd_key(1536, 1536, jnp.bfloat16),
        autotune.moe_bwd_dx_key(1536, 1536, jnp.bfloat16),
        autotune.tgmm_key(1536, 1536, jnp.bfloat16),
    ):
        entry = autotune.lookup(key, chip="TPU v5 lite")
        assert entry is not None, f"missing committed default: {key}"
        names = ("tm", "tn", "ic") if ":dx:" in key or "bwd_dx" in key else (
            "tm", "tk", "tn"
        )
        assert autotune.valid_tiles(entry, names, None) is not None, key
    for key in (
        autotune.attn_key(64, 128, True),
        autotune.attn_key(64, None, True),
        autotune.attn_key(128, None, True),
    ):
        entry = autotune.lookup(key, chip="TPU v5 lite")
        assert entry is not None, f"missing committed default: {key}"
        assert entry.get("backend") in ("splash", "block"), key
        assert autotune.valid_tiles(
            entry, ("block_q", "block_kv"), None
        ) is not None, key


def test_committed_defaults_resolve_through_kernel_tile_pickers():
    """The tile-resolution helpers next to each kernel must actually CONSUME
    the committed v5e entries (not silently fall back) — pinned by faking
    the chip kind."""
    import automodel_tpu.ops.fused_expert_mlp as fm
    import automodel_tpu.ops.grouped_matmul as gm

    orig = autotune.chip_key
    autotune.chip_key = lambda: "TPU v5 lite"
    try:
        table = json.loads(autotune.DEFAULTS_PATH.read_text())
        v5e = table["chips"]["TPU v5 lite"]
        e = v5e[autotune.moe_bwd_gu_key(1536, 1536, jnp.bfloat16)]
        assert fm._bwd_gu_tiles(1536, 1536, jnp.bfloat16) == (
            e["tm"], e["tk"], e["tn"]
        )
        e = v5e[autotune.moe_bwd_dwd_key(1536, 1536, jnp.bfloat16)]
        assert fm._bwd_dwd_tiles(1536, 1536, jnp.bfloat16) == (
            e["tm"], e["tk"], e["tn"]
        )
        e = v5e[autotune.moe_bwd_dx_key(1536, 1536, jnp.bfloat16)]
        assert fm._bwd_dx_tiles(1536, 1536, jnp.bfloat16) == (
            e["tm"], e["tn"], e["ic"]
        )
        e = v5e[autotune.tgmm_key(1536, 1536, jnp.bfloat16)]
        assert gm._tgmm_tiles(1536, 1536, jnp.bfloat16) == (
            e["tm"], e["tk"], e["tn"]
        )
        from automodel_tpu.ops.attention import _autotune_entry

        # the windowed head_dim-64 shape: splash with small kv blocks until
        # a measured sweep says otherwise (see autotune_defaults.json)
        entry = _autotune_entry(64, 128, True)
        assert entry is not None and entry["backend"] == "splash"
        assert (entry["block_q"], entry["block_kv"]) == (256, 128)
    finally:
        autotune.chip_key = orig


def test_runtime_table_shadows_defaults(tmp_path, monkeypatch):
    key = autotune.tgmm_key(1536, 1536, jnp.bfloat16)
    path = tmp_path / "t.json"
    path.write_text(json.dumps({
        "format_version": 1,
        "chips": {"TPU v5 lite": {key: {"tm": 2048, "tk": 256, "tn": 256}}},
    }))
    monkeypatch.setenv(autotune.ENV_TABLE, str(path))
    autotune.clear_cache()
    entry = autotune.lookup(key, chip="TPU v5 lite")
    assert entry == {"tm": 2048, "tk": 256, "tn": 256}
    # other keys still resolve from the committed defaults
    assert autotune.lookup(
        autotune.moe_bwd_gu_key(1536, 1536, jnp.bfloat16), chip="TPU v5 lite"
    ) is not None


def test_infeasible_or_malformed_entries_rejected(tmp_path, monkeypatch):
    """Bad table entries must cost tuning, never correctness: non-128
    multiples, non-ints, and VMEM-busting tiles all fall back."""
    import automodel_tpu.ops.grouped_matmul as gm

    key = autotune.tgmm_key(64, 64, jnp.float32)
    bad = [
        {"tm": 100, "tk": 128, "tn": 128},        # not 128-aligned
        {"tm": "512", "tk": 128, "tn": 128},      # wrong type
        {"tm": 128, "tk": 128},                   # missing name
        {"tm": 8192, "tk": 4096, "tn": 4096},     # VMEM-infeasible
    ]
    fallback = None
    for i, entry in enumerate(bad):
        path = tmp_path / f"bad{i}.json"
        path.write_text(json.dumps({
            "format_version": 1, "chips": {autotune.chip_key(): {key: entry}},
        }))
        monkeypatch.setenv(autotune.ENV_TABLE, str(path))
        autotune.clear_cache()
        tiles = gm._tgmm_tiles(64, 64, jnp.float32)
        if fallback is None:
            fallback = tiles
        assert tiles == fallback, f"bad entry {entry} was not rejected"


def test_save_table_roundtrip_and_merge(tmp_path):
    path = tmp_path / "out.json"
    autotune.save_table(path, {"k1": {"tm": 128}}, chip="chipA")
    autotune.save_table(path, {"k2": {"tm": 256}}, chip="chipA")
    autotune.save_table(path, {"k1": {"tm": 512}}, chip="chipB")
    data = json.loads(path.read_text())
    assert data["chips"]["chipA"] == {"k1": {"tm": 128}, "k2": {"tm": 256}}
    assert data["chips"]["chipB"] == {"k1": {"tm": 512}}
    assert autotune.lookup("k2", chip="chipA") is None  # not in defaults
    os.environ[autotune.ENV_TABLE] = str(path)
    try:
        autotune.clear_cache()
        assert autotune.lookup("k2", chip="chipA") == {"tm": 256}
    finally:
        del os.environ[autotune.ENV_TABLE]
        autotune.clear_cache()


def test_garbage_table_file_reads_empty(tmp_path, monkeypatch):
    path = tmp_path / "garbage.json"
    path.write_text("{not json")
    monkeypatch.setenv(autotune.ENV_TABLE, str(path))
    autotune.clear_cache()
    assert autotune.lookup("anything", chip="cpu") is None
    info = autotune.table_info(chip="cpu")
    assert info["chip"] == "cpu"


def test_kernel_bench_cpu_end_to_end(tmp_path):
    """The sweep driver runs on CPU (interpret mode) end-to-end: writes the
    per-chip table (loadable by the registry), the markdown report, and a
    JSONL accepted by telemetry/report.py --strict with the kernel_* keys
    summarized."""
    out = tmp_path / "kb"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "kernel_bench.py"),
         "--output-dir", str(out), "--shapes", "small"],
        capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    table = out / "autotune_cpu.json"
    assert table.exists()
    data = json.loads(table.read_text())
    cpu = data["chips"]["cpu"]
    # both kernel families produced loadable winners
    assert any(k.startswith("moe_bwd_gu:") for k in cpu)
    assert any(k.startswith("attn:h64:") for k in cpu)
    md = (out / "KERNEL_BENCH.md").read_text()
    # off-TPU the report must NOT claim raced winners — gate language only
    assert "Gate survivors" in md and "interpret" in md
    # the only-viable-backend rule: the attn entry records it was not raced
    attn_key = next(k for k in cpu if k.startswith("attn:h64:"))
    assert "not raced" in cpu[attn_key]["source"]
    # the JSONL rides the standard report pipeline
    from automodel_tpu.telemetry.report import (
        lint_metrics_jsonl,
        summarize_metrics,
    )

    records, problems = lint_metrics_jsonl(str(out / "kernel_bench.jsonl"))
    assert not problems, problems[:5]
    summary = summarize_metrics(records)
    assert summary["kernel_bench_records"] >= 6
    # this build's splash kernel can't run head_dim 64 — recorded, not fatal
    assert summary.get("kernel_bench_failures", 0) >= 1
    # the written table round-trips through the registry
    os.environ[autotune.ENV_TABLE] = str(table)
    try:
        autotune.clear_cache()
        assert autotune.lookup(
            autotune.moe_bwd_gu_key(128, 128, jnp.float32), chip="cpu"
        ) is not None
    finally:
        del os.environ[autotune.ENV_TABLE]
        autotune.clear_cache()
