"""Host-overlap input pipeline (data/prefetch.py): stream parity with the
sync path, consumption-cursor resume semantics (prefetched-but-unconsumed
batches replay exactly once), rollback across a prefetched window, the
overlap itself (injected collate delay hidden behind consumer work), and the
e2e determinism contract — loss trajectory bit-identical sync vs prefetch
vs resume-after-kill."""

import json
import os
import signal
import time
from pathlib import Path

import numpy as np
import pytest

import jax

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.data.collators import stack_microbatches
from automodel_tpu.data.loader import DataLoader
from automodel_tpu.data.prefetch import (
    PrefetchConfig,
    PrefetchingLoader,
    PreparedBatch,
)
from automodel_tpu.data.sft import MockSFTDataset


def _sync_groups(ds, gbs, group_size, seed=0, epochs=1):
    """The sync reference stream: stacked grad-acc groups, tail discarded
    (exactly what StepScheduler's grouping feeds the train loop)."""
    out = []
    loader = DataLoader(ds, global_batch_size=gbs, shuffle=True, seed=seed)
    for _ in range(epochs):
        group = []
        for b in loader:
            group.append(b)
            if len(group) == group_size:
                out.append(stack_microbatches(group))
                group = []
    return out


def _facade(ds, gbs, group_size, depth=3, workers=2, seed=0):
    return PrefetchingLoader(
        DataLoader(ds, global_batch_size=gbs, shuffle=True, seed=seed),
        PrefetchConfig(depth=depth, collate_workers=workers),
        group_size=group_size,
    )


def _assert_batches_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def test_prefetch_stream_parity_and_tail_discard():
    """40 samples / gbs 4 / grad_acc 3 → 10 batches, 3 full groups per
    epoch (tail discarded) — bit-identical to the sync grouping, across an
    epoch boundary."""
    ds = MockSFTDataset(vocab_size=64, seq_length=8, num_samples=40, seed=0)
    ref = _sync_groups(ds, 4, 3, epochs=2)
    assert len(ref) == 6
    pf = _facade(ds, 4, 3)
    got = []
    for _ in range(2):  # one __iter__ call per epoch, like the scheduler
        got.extend(item.host for item in pf)
    pf.close()
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        _assert_batches_equal(a, b)


def test_consumption_cursor_not_fetch_cursor():
    """With depth 3 the producer runs well ahead; state_dict() must track
    only what the consumer popped. A fresh pipeline restored from the
    snapshot replays the unconsumed remainder exactly once — no gap (a
    fetch-cursor state would skip the prefetched window), no repeat."""
    ds = MockSFTDataset(vocab_size=64, seq_length=8, num_samples=48, seed=1)
    ref = _sync_groups(ds, 4, 2, seed=1)
    assert len(ref) == 6
    pf = _facade(ds, 4, 2, depth=3, seed=1)
    it = iter(pf)
    consumed = [next(it).host, next(it).host]
    # let the producer run ahead of the consumer before snapshotting
    deadline = time.monotonic() + 5
    while pf.queue_depth < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pf.queue_depth >= 1
    snap = pf.state_dict()
    assert snap["batch_in_epoch"] == 4  # 2 groups x 2 batches CONSUMED
    pf.close()  # simulated kill: run-ahead dropped

    pf2 = _facade(ds, 4, 2, depth=3, seed=999)  # seed restored from snap
    pf2.load_state_dict(snap)
    replayed = [item.host for item in pf2]
    pf2.close()
    seen = consumed + replayed
    assert len(seen) == len(ref)
    for a, b in zip(seen, ref):
        _assert_batches_equal(a, b)


def test_seek_flushes_run_ahead_and_replays_exactly():
    """seek() (the rollback fast-forward entry point) joins the producer,
    drops everything fetched ahead, and restarts at the exact cursor."""
    ds = MockSFTDataset(vocab_size=64, seq_length=8, num_samples=40, seed=2)
    ref = _sync_groups(ds, 4, 1, seed=2, epochs=2)
    pf = _facade(ds, 4, 1, depth=4, seed=2)
    it = iter(pf)
    for _ in range(6):
        next(it)
    # roll back INTO the already-consumed region, then fast-forward past an
    # epoch boundary — both directions must land bit-exactly
    pf.seek(0, 3)
    assert pf.state_dict()["batch_in_epoch"] == 3
    tail = [item.host for item in pf]  # rest of epoch 0
    tail += [item.host for item in pf]  # epoch 1
    pf.close()
    for a, b in zip(tail, ref[3:]):
        _assert_batches_equal(a, b)
    assert len(tail) == len(ref) - 3


def test_seed_change_invalidates_cached_epoch_order():
    """load_state_dict may carry a different seed than the warm loader's;
    a stale cached shuffle order would silently replay the old stream."""
    ds = MockSFTDataset(vocab_size=64, seq_length=8, num_samples=24, seed=0)
    warm = DataLoader(ds, global_batch_size=4, shuffle=True, seed=1)
    next(iter(warm))  # epoch-0 order now cached under seed 1
    warm.load_state_dict({"epoch": 0, "batch_in_epoch": 0, "seed": 2})
    fresh = DataLoader(ds, global_batch_size=4, shuffle=True, seed=2)
    _assert_batches_equal(warm.batch_for(0, 0), fresh.batch_for(0, 0))


def test_producer_exception_surfaces_at_pop():
    class Boom:
        def __len__(self):
            return 12

        def __getitem__(self, i):
            if i >= 6:
                raise RuntimeError("shard went away")
            return {"input_ids": [1, 2, 3]}

    pf = _facade(Boom(), 2, 1, depth=2, workers=1)
    it = iter(pf)
    with pytest.raises(RuntimeError, match="shard went away"):
        for _ in range(10):
            next(it)
    pf.close()


def test_overlap_hides_injected_collate_delay():
    """The headline property, loader-level so it is robust to CI load: with
    a 40ms injected collate delay and ~25ms of consumer work per step, the
    prefetched pipeline must run >= 1.5x the sync loop (the theoretical
    ratio here is ~2.4x: 65ms serial vs max(25, 40/4)ms overlapped)."""
    from automodel_tpu.resilience.fault_injection import activate

    ds = MockSFTDataset(vocab_size=64, seq_length=8, num_samples=160, seed=3)
    steps, work_s = 12, 0.025
    activate({"slow_collate_ms": 40.0})
    try:
        sync = DataLoader(ds, global_batch_size=4, shuffle=True, seed=3)
        it = iter(sync)
        t0 = time.perf_counter()
        for _ in range(steps):
            next(it)
            time.sleep(work_s)  # stands in for device compute
        t_sync = time.perf_counter() - t0

        pf = _facade(ds, 4, 1, depth=4, workers=4, seed=3)
        it = iter(pf)
        next(it)  # warm the pipeline (the train loop's compile step)
        time.sleep(0.3)
        t0 = time.perf_counter()
        for _ in range(steps):
            next(it)
            time.sleep(work_s)
        t_pf = time.perf_counter() - t0
        pf.close()
    finally:
        activate(None)
    speedup = t_sync / t_pf
    assert speedup >= 1.5, (
        f"prefetch only {speedup:.2f}x over sync "
        f"(sync {t_sync:.3f}s, prefetched {t_pf:.3f}s for {steps} steps)"
    )


def test_report_strict_and_metrics_gauges(tmp_path):
    """`report --strict` accepts the new keys (numeric or null+marker) and
    the /metrics exporter publishes them as gauges under its lock."""
    from automodel_tpu.telemetry.prometheus import TrainMetricsExporter
    from automodel_tpu.telemetry.report import (
        lint_metrics_jsonl,
        summarize_metrics,
        validate_bench_result,
    )

    p = tmp_path / "m.jsonl"
    p.write_text(
        json.dumps(
            {"step": 1, "ts": 1.0, "loss": 2.0, "host_input_wait_s": 0.012,
             "prefetch_depth": 3}
        )
        + "\n"
        + json.dumps({"step": 2, "ts": 2.0, "loss": 1.9, "host_input_wait_s": "slow"})
        + "\n"
    )
    records, problems = lint_metrics_jsonl(str(p))
    assert len(records) == 2
    assert any("host_input_wait_s is not numeric" in x for x in problems)
    assert summarize_metrics(records)["host_input_wait_s_mean"] == pytest.approx(0.012)

    ex = TrainMetricsExporter()
    ex.update({"step": 1, "host_input_wait_s": 0.034, "prefetch_depth": 2})
    body = ex.registry.render()
    assert "automodel_train_host_input_wait_seconds 0.034" in body
    assert "automodel_train_prefetch_queue_depth 2" in body

    # bench sub-leg contract: null speedup must carry a reason; a literal
    # 0.0 is never a measurement
    assert validate_bench_result({"input_pipeline_speedup": None}) != []
    assert validate_bench_result({"input_pipeline_speedup": 0.0}) != []
    assert validate_bench_result(
        {"input_pipeline_speedup": None, "input_pipeline_failure": "no cpu"}
    ) == []
    assert validate_bench_result(
        {"input_pipeline_speedup": 3.1, "input_pipeline_failure": None}
    ) == []


# -- e2e: recipe-level determinism + exactly-once replay ----------------------


def _recipe_cfg(tmp_path: Path, tag: str, extra: dict | None = None) -> ConfigNode:
    cfg = {
        "seed": 7,
        "model": {
            "hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "model_type": "llama",
                "vocab_size": 128,
                "hidden_size": 64,
                "intermediate_size": 128,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
                "num_key_value_heads": 2,
                "max_position_embeddings": 128,
            },
            "backend": {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32"},
        },
        "distributed": {"dp_shard": 4, "tp": 2},
        "dataset": {
            "_target_": "automodel_tpu.data.sft.MockSFTDataset",
            "vocab_size": 128,
            "seq_length": 32,
            "num_samples": 64,
        },
        "dataloader": {"global_batch_size": 8},
        "step_scheduler": {
            "grad_acc_steps": 1, "num_epochs": 2, "max_steps": 6,
            "ckpt_every_steps": 1, "log_every_steps": 1,
        },
        "optimizer": {"name": "adamw", "lr": 1e-3, "grad_clip_norm": 1.0},
        "loss_fn": {"name": "masked_ce"},
        "checkpoint": {"enabled": True, "checkpoint_dir": str(tmp_path / f"ckpt_{tag}")},
        "logging": {"metrics_path": str(tmp_path / f"metrics_{tag}.jsonl")},
    }
    for k, v in (extra or {}).items():
        cfg[k] = v
    return ConfigNode(cfg)


def _losses_by_step(path: Path) -> dict[int, float]:
    out: dict[int, float] = {}
    for line in path.read_text().splitlines():
        rec = json.loads(line)
        if "loss" in rec and isinstance(rec.get("step"), int):
            out[rec["step"]] = rec["loss"]  # last occurrence wins (replays)
    return out


PREFETCH = {"data": {"prefetch": {"depth": 3, "collate_workers": 2}}}


@pytest.fixture(scope="module")
def sync_reference(tmp_path_factory, devices8, monkeypatch_module):
    """One uninterrupted SYNC run — the trajectory every prefetch variant
    must reproduce bit-exactly."""
    tmp = tmp_path_factory.mktemp("prefetch_ref")
    from automodel_tpu.recipes.train_ft import main

    last = main(_recipe_cfg(tmp, "sync"))
    assert int(last["step"]) == 6
    return _losses_by_step(tmp / "metrics_sync.jsonl")


@pytest.fixture(scope="module")
def monkeypatch_module(devices8):
    mp = pytest.MonkeyPatch()
    mp.setattr(jax, "devices", lambda *a: devices8)
    yield mp
    mp.undo()


def test_e2e_prefetch_loss_trajectory_bit_identical(
    tmp_path, devices8, monkeypatch_module, sync_reference
):
    from automodel_tpu.recipes.train_ft import main

    last = main(_recipe_cfg(tmp_path, "pf", PREFETCH))
    assert int(last["step"]) == 6
    got = _losses_by_step(tmp_path / "metrics_pf.jsonl")
    assert got == sync_reference  # bit-identical, every step


def test_e2e_kill_mid_prefetch_replays_exactly_once(
    tmp_path, devices8, monkeypatch_module, sync_reference
):
    """Kill at step 4 with the producer running ahead (slow collate keeps
    the queue mid-flight), restart, finish. The merged per-step trajectory
    must equal the uninterrupted sync run's — a batch trained twice or
    skipped would shift every subsequent loss."""
    from automodel_tpu.recipes.train_ft import TrainFinetuneRecipeForNextTokenPrediction
    from automodel_tpu.resilience import InjectedFault

    cfg = _recipe_cfg(
        tmp_path, "kill",
        {
            **PREFETCH,
            "fault_injection": {
                "die_at_step": 4, "die_mode": "exception", "slow_collate_ms": 20,
            },
        },
    )
    r1 = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    r1.setup()
    with pytest.raises(InjectedFault):
        r1.run_train_validation_loop()

    # restart WITHOUT the fault (transient kill); auto-resumes the newest
    # committed checkpoint and replays the unconsumed window exactly once
    cfg2 = _recipe_cfg(tmp_path, "kill", {**PREFETCH, "fault_injection": {}})
    r2 = TrainFinetuneRecipeForNextTokenPrediction(cfg2)
    r2.setup()
    assert int(r2.state.step) < 4  # resumed strictly before the kill
    last = r2.run_train_validation_loop()
    assert int(last["step"]) == 6
    got = _losses_by_step(tmp_path / "metrics_kill.jsonl")
    assert got == sync_reference


def test_e2e_rollback_across_prefetched_window(
    tmp_path, devices8, monkeypatch_module
):
    """on_nonfinite=rollback with the pipeline running ahead: the restore +
    fast-forward must flush the run-ahead and re-seek (a stale prefetched
    batch would retrain the offending window). Sync and prefetched arms of
    the SAME transient divergence must converge to identical final losses."""
    import jax.numpy as jnp

    from automodel_tpu.recipes.train_ft import TrainFinetuneRecipeForNextTokenPrediction

    def run(tag, extra):
        cfg = _recipe_cfg(
            tmp_path, tag,
            {**extra, "fault_tolerance": {"on_nonfinite": "rollback"}},
        )
        r = TrainFinetuneRecipeForNextTokenPrediction(cfg)
        r.setup()
        orig_step, fired = r.train_step, []

        def flaky_step(state, batch):
            state, m = orig_step(state, batch)
            if int(jax.device_get(m["step"])) == 3 and not fired:
                fired.append(1)
                m = dict(m)
                m["nonfinite"] = jnp.bool_(True)  # transient divergence
            return state, m

        r.train_step = flaky_step
        last = r.run_train_validation_loop()
        assert int(last["step"]) == 6
        assert last["rollbacks_total"] == 1
        return r, _losses_by_step(tmp_path / f"metrics_{tag}.jsonl")

    r_sync, sync_losses = run("rb_sync", {})
    r_pf, pf_losses = run("rb_pf", PREFETCH)
    assert pf_losses == sync_losses
    # both arms resumed their loaders at the same consumption cursor
    s1, s2 = r_sync.dataloader.state_dict(), r_pf.dataloader.state_dict()
    assert (s1["epoch"], s1["batch_in_epoch"]) == (s2["epoch"], s2["batch_in_epoch"])


def test_preemption_drain_joins_prefetch_worker(tmp_path, devices8, monkeypatch_module):
    """SIGTERM-style drain: the loop stops at the step boundary, the
    prefetch producer is JOINED before the emergency save, and the saved
    cursor (consumption, not fetch) resumes the next run exactly."""
    from automodel_tpu.recipes.train_ft import TrainFinetuneRecipeForNextTokenPrediction
    from automodel_tpu.resilience import TrainingPreempted

    cfg = _recipe_cfg(
        tmp_path, "drain",
        {
            **PREFETCH,
            "step_scheduler": {
                "grad_acc_steps": 1, "num_epochs": 2, "max_steps": 50,
                "ckpt_every_steps": 0, "log_every_steps": 1,
            },
        },
    )
    r = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    r.setup()
    orig_step, n = r.train_step, []

    def step_then_preempt(state, batch):
        out = orig_step(state, batch)
        n.append(1)
        if len(n) == 3:
            os.kill(os.getpid(), signal.SIGTERM)
        return out

    r.train_step = step_then_preempt
    with pytest.raises(TrainingPreempted):
        r.run_train_validation_loop()
    # producer joined (no thread left behind), run-ahead dropped
    assert r.dataloader._thread is None
    assert r.dataloader.queue_depth == 0
    # the emergency checkpoint's cursor is the consumption cursor: 3 steps
    # x 1 batch consumed, regardless of how far the producer had fetched
    assert r.dataloader.state_dict()["batch_in_epoch"] == 3
