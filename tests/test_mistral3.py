"""Mistral3 VLM: HF numerical parity (Pixtral tower with 2-D rope +
per-image block attention, spatial patch merger, projector, image-feature
scatter into the Mistral text stack) and adapter round-trip. Reference
parity target: components/models/mistral3."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.common.config import BackendConfig
from automodel_tpu.models.mistral3 import (
    Mistral3Config,
    Mistral3ForConditionalGeneration,
    Mistral3StateDictAdapter,
)

FP32 = BackendConfig(
    attn="sdpa", param_dtype="float32", compute_dtype="float32",
    experts="dense", scan_layers=False,
)

IMG_TOKEN = 10
IMAGE_SIZE = 32  # 4x4 patch grid at ps=8 → 2x2 merged tokens per image
PATCH = 8
N_MERGED = 4


def _hf_tiny():
    import torch

    torch.manual_seed(0)
    from transformers.models.mistral3.configuration_mistral3 import (
        Mistral3Config as HFConfig,
    )
    from transformers.models.mistral3.modeling_mistral3 import (
        Mistral3ForConditionalGeneration as HFModel,
    )

    cfg = HFConfig(
        text_config=dict(
            model_type="mistral", vocab_size=128, hidden_size=32,
            intermediate_size=64, num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=8, max_position_embeddings=256,
            rope_theta=10_000.0, sliding_window=None, rms_norm_eps=1e-6,
            attn_implementation="eager",
        ),
        vision_config=dict(
            model_type="pixtral", hidden_size=16, intermediate_size=32,
            num_hidden_layers=2, num_attention_heads=2, image_size=IMAGE_SIZE,
            patch_size=PATCH, hidden_act="gelu", attn_implementation="eager",
        ),
        image_token_index=IMG_TOKEN,
        multimodal_projector_bias=False,
        spatial_merge_size=2,
        projector_hidden_act="gelu",
        attn_implementation="eager",
    )
    return cfg, HFModel(cfg).eval()


def _native_from_hf(hf_cfg, hf_model):
    cfg = Mistral3Config.from_hf(hf_cfg.to_dict())
    model = Mistral3ForConditionalGeneration(cfg, FP32)
    adapter = Mistral3StateDictAdapter(cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    params = adapter.from_hf(lambda k: sd[k])
    params = jax.tree.map(jnp.asarray, params)
    return cfg, model, params, sd


@pytest.fixture(scope="module")
def parity_setup():
    hf_cfg, hf_model = _hf_tiny()
    cfg, model, params, sd = _native_from_hf(hf_cfg, hf_model)
    return hf_cfg, hf_model, cfg, model, params, sd


def _mk_inputs(rng, batch=2, seq=12):
    ids = rng.integers(11, 100, size=(batch, seq)).astype(np.int64)
    for b in range(batch):
        ids[b, 1 + b : 1 + b + N_MERGED] = IMG_TOKEN
    pixels = rng.normal(size=(batch, 3, IMAGE_SIZE, IMAGE_SIZE)).astype(np.float32)
    sizes = np.tile([[IMAGE_SIZE, IMAGE_SIZE]], (batch, 1))
    return ids, pixels, sizes


def test_logits_parity_with_images(parity_setup):
    import torch

    _, hf_model, cfg, model, params, _ = parity_setup
    rng = np.random.default_rng(0)
    ids, pixels, sizes = _mk_inputs(rng)
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.tensor(ids),
            pixel_values=torch.tensor(pixels),
            image_sizes=torch.tensor(sizes),
        ).logits.numpy()

    got = np.asarray(
        model(params, jnp.asarray(ids), pixel_values=jnp.asarray(pixels))
    )
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)


def test_logits_parity_text_only(parity_setup):
    import torch

    _, hf_model, cfg, model, params, _ = parity_setup
    rng = np.random.default_rng(1)
    ids = rng.integers(11, 100, size=(2, 9)).astype(np.int64)
    with torch.no_grad():
        ref = hf_model(input_ids=torch.tensor(ids)).logits.numpy()
    got = np.asarray(model(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)


def test_adapter_round_trip(parity_setup):
    _, _, cfg, _, params, sd = parity_setup
    adapter = Mistral3StateDictAdapter(cfg)
    out = dict(adapter.to_hf(jax.tree.map(np.asarray, params)))
    assert set(out) == set(sd)
    for k, v in sd.items():
        np.testing.assert_allclose(out[k], v, atol=1e-6, err_msg=k)


def test_registry_resolves():
    from automodel_tpu.models.registry import resolve_architecture

    builder = resolve_architecture(
        {"architectures": ["Mistral3ForConditionalGeneration"]}
    )
    hf_cfg, _ = _hf_tiny()
    model, adapter = builder(hf_cfg.to_dict(), FP32)
    assert isinstance(model, Mistral3ForConditionalGeneration)
    assert isinstance(adapter, Mistral3StateDictAdapter)
    p = model.init(jax.random.PRNGKey(0))
    assert "vision" in p and "projector" in p and "text" in p
