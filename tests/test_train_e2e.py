"""End-to-end training slice (SURVEY.md §7 'minimum end-to-end slice'):
YAML recipe → mesh → model → jitted train steps → metrics JSONL → checkpoint
save/restore → consolidated HF save. Runs on virtual CPU devices."""

import json
import os
from pathlib import Path

import numpy as np
import pytest

import jax

from automodel_tpu.config.loader import ConfigNode


def _recipe_cfg(tmp_path: Path, extra: dict | None = None) -> ConfigNode:
    cfg = {
        "seed": 7,
        "model": {
            "hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "model_type": "llama",
                "vocab_size": 128,
                "hidden_size": 64,
                "intermediate_size": 128,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
                "num_key_value_heads": 2,
                "max_position_embeddings": 128,
            },
            "backend": {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32"},
        },
        "distributed": {"dp_shard": 4, "tp": 2},
        "dataset": {
            "_target_": "automodel_tpu.data.sft.MockSFTDataset",
            "vocab_size": 128,
            "seq_length": 32,
            "num_samples": 64,
        },
        "dataloader": {"global_batch_size": 8},
        "step_scheduler": {"grad_acc_steps": 2, "num_epochs": 1, "max_steps": 4},
        "optimizer": {"name": "adamw", "lr": 1e-3, "grad_clip_norm": 1.0},
        "loss_fn": {"name": "masked_ce"},
        "checkpoint": {"enabled": True, "checkpoint_dir": str(tmp_path / "ckpt"),
                        "save_consolidated": True},
        "logging": {"metrics_path": str(tmp_path / "metrics.jsonl")},
    }
    for k, v in (extra or {}).items():
        cfg[k] = v
    return ConfigNode(cfg)


def test_e2e_train_loop(tmp_path, devices8, monkeypatch):
    # force build_mesh to use the virtual cpu devices
    import automodel_tpu.parallel.mesh as mesh_mod

    monkeypatch.setattr(jax, "devices", lambda *a: devices8)

    from automodel_tpu.recipes.train_ft import main

    cfg = _recipe_cfg(tmp_path)
    last = main(cfg)
    assert last["step"] == 4
    assert np.isfinite(last["loss"])

    # metrics JSONL written
    lines = [json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert len(lines) >= 4
    losses = [l["loss"] for l in lines if "loss" in l]
    assert losses[-1] < losses[0]  # tiny model on mock data must improve

    # checkpoint exists with sharded state + consolidated HF export
    ckpt_dirs = list((tmp_path / "ckpt").iterdir())
    assert ckpt_dirs
    final = max(ckpt_dirs, key=lambda p: int(p.name.rsplit("_", 1)[1]))
    assert (final / "state").exists()
    assert (final / "hf" / "model.safetensors").exists()

    # the consolidated HF export reloads through the HF reader
    from automodel_tpu.checkpoint.hf_io import HFCheckpointReader

    reader = HFCheckpointReader(final / "hf")
    assert "model.embed_tokens.weight" in reader.keys()
    emb = reader.get_tensor("model.embed_tokens.weight")
    assert emb.shape == (128, 64)


def test_e2e_resume(tmp_path, devices8, monkeypatch):
    monkeypatch.setattr(jax, "devices", lambda *a: devices8)
    from automodel_tpu.recipes.train_ft import TrainFinetuneRecipeForNextTokenPrediction

    cfg = _recipe_cfg(tmp_path, {"step_scheduler": {"grad_acc_steps": 1, "num_epochs": 1,
                                                     "max_steps": 2, "ckpt_every_steps": 2}})
    r1 = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    r1.setup()
    r1.run_train_validation_loop()
    step1 = int(r1.state.step)
    assert step1 == 2

    # new recipe picks up the latest checkpoint automatically
    r2 = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    r2.setup()
    assert int(r2.state.step) == step1
    # params actually match
    a = jax.device_get(r1.state.params["final_norm"]["scale"])
    b = jax.device_get(r2.state.params["final_norm"]["scale"])
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
