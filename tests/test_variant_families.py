"""Mixtral / Qwen2-MoE / Phi3: HF parity through the conversion-mapping
path — the checkpoint is written with the VARIANT key layout
(block_sparse_moe w1/w3/w2, shared_expert singular, fused qkv/gate_up) and
loaded through auto_model.from_pretrained, so the remaps are exercised end
to end."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu import auto_model
from automodel_tpu.checkpoint.hf_io import save_hf_checkpoint

FP32 = {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32",
        "experts": "dense"}


def _save(tmp_path, hf_model, arch):
    """Write the checkpoint the way the hub does: full serialized config
    (all defaults materialized — avoids dict-vs-object default drift) +
    safetensors weights."""
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    save_hf_checkpoint(tmp_path, list(sd.items()))
    cfg_dict = hf_model.config.to_dict()
    cfg_dict["architectures"] = [arch]
    (tmp_path / "config.json").write_text(json.dumps(cfg_dict, default=str))
    return tmp_path


def _parity(tmp_path, hf_model, arch, atol=3e-4, roundtrip=True):
    import torch

    d = _save(tmp_path, hf_model, arch)
    auto = auto_model.from_pretrained(str(d), None, FP32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, hf_model.config.vocab_size, size=(2, 10)).astype(np.int64)
    with torch.no_grad():
        ref = hf_model(input_ids=torch.from_numpy(ids)).logits.numpy()
    out = auto.model(auto.params, jnp.asarray(ids))
    logits = out[0] if isinstance(out, tuple) else out
    np.testing.assert_allclose(np.asarray(logits), ref, atol=atol, rtol=2e-3)
    if roundtrip:
        # save-side key dialect: exported checkpoints reload in the ORIGINAL arch
        sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
        out_keys = {k for k, _ in auto.adapter.to_hf(jax.device_get(auto.params))}
        assert out_keys == set(sd), (set(sd) ^ out_keys)


def test_mixtral_parity(tmp_path):
    import torch

    torch.manual_seed(0)
    from transformers import MixtralConfig, MixtralForCausalLM

    kw = dict(
        vocab_size=96, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        head_dim=16, num_local_experts=4, num_experts_per_tok=2,
        sliding_window=None, rope_theta=1e6, attn_implementation="eager",
    )
    m = MixtralForCausalLM(MixtralConfig(**kw)).eval()
    _parity(tmp_path, m, "MixtralForCausalLM")


def test_qwen2_moe_parity(tmp_path):
    import torch

    torch.manual_seed(0)
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    kw = dict(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, shared_expert_intermediate_size=24,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[], attn_implementation="eager",
    )
    m = Qwen2MoeForCausalLM(Qwen2MoeConfig(**kw)).eval()
    _parity(tmp_path, m, "Qwen2MoeForCausalLM")


def test_phi3_parity(tmp_path):
    import torch

    torch.manual_seed(0)
    from transformers import Phi3Config, Phi3ForCausalLM

    kw = dict(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        pad_token_id=0, attn_implementation="eager",
    )
    m = Phi3ForCausalLM(Phi3Config(**kw)).eval()
    # exports use canonical split keys; a fused-qkv save dialect is pending
    _parity(tmp_path, m, "Phi3ForCausalLM", roundtrip=False)
