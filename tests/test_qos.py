"""Multi-tenant QoS acceptance (docs/serving.md "Multi-tenant QoS").

Engine side: tiered admission (EDF within tier, WFQ across tenants,
aging promotion), per-tenant token-bucket quotas with the retriable
`quota` reason, strictly lowest-tier-first overload shedding, the
record_shed/record_quota one-record-per-give-up seams, and the
drain-beats-every-tier rule. The overload e2e drives ~2x-capacity
Poisson mixed-tier load with invariants audited after every scheduler
event; the noisy-neighbor chaos leg floods one tenant via
fault_injection `serve_tenant_flood` and proves isolation.

Fleet side: per-tier/per-tenant /metrics labels, federation rollups of
the labeled families, the per-tier SLO burn objective (labels:
selector), the fleet-status TIER/TENANT tables, the report summary's
per-tier histograms, and the docs reason-table drift guard.

All CPU-fast, tier-1."""

import json
import re
import time
from pathlib import Path

import numpy as np
import pytest

import jax

from automodel_tpu.auto_model import AutoModel
from automodel_tpu.generation.engine import GenerationConfig
from automodel_tpu.models.common.config import BackendConfig, TransformerConfig
from automodel_tpu.resilience import fault_injection as fi
from automodel_tpu.serving.engine import (
    COMPLETION_REASONS,
    TIERS,
    EngineDraining,
    QoSConfig,
    QueueFull,
    QuotaExceeded,
    ServeConfig,
    ServingEngine,
    TenantConfig,
    tier_index,
)
from automodel_tpu.telemetry.federation import (
    Federation,
    fleet_name,
    parse_exposition,
)
from automodel_tpu.telemetry.prometheus import MetricsRegistry

FP32 = BackendConfig(attn="sdpa", param_dtype="float32", compute_dtype="float32")

DOCS = Path(__file__).resolve().parent.parent / "docs"


@pytest.fixture(autouse=True)
def _clear_injector():
    yield
    fi.activate(None)


def _tiny_auto():
    from automodel_tpu.models.llama import LlamaForCausalLM

    model = LlamaForCausalLM(
        TransformerConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
            num_heads=4, num_kv_heads=2, head_dim=8,
        ),
        FP32,
    )
    return AutoModel(
        model=model, params=model.init(jax.random.key(0)),
        adapter=None, mesh_ctx=None,
    )


def _tenants(**extra):
    base = {
        "chat": TenantConfig(tier="interactive", weight=2.0),
        "ebatch": TenantConfig(tier="batch"),
        "scraper": TenantConfig(tier="best_effort"),
    }
    base.update(extra)
    return base


def _qos_engine(records, qos=None, **serve_over):
    serve_over.setdefault("slots", 2)
    return ServingEngine(
        _tiny_auto(),
        ServeConfig(
            block_size=4, num_blocks=48, prefill_chunk=4, max_seq_len=32,
            qos=qos if qos is not None else QoSConfig(
                enabled=True, tenants=_tenants()
            ),
            **serve_over,
        ),
        GenerationConfig(max_new_tokens=4, greedy=True),
        on_record=records.append,
    )


# ---------------------------------------------------------------------------
# tier order / config
# ---------------------------------------------------------------------------


def test_tier_order_and_unknown_tier_rejected():
    assert TIERS == ("interactive", "batch", "best_effort")
    assert [tier_index(t) for t in TIERS] == [0, 1, 2]
    with pytest.raises(ValueError, match="unknown QoS tier"):
        tier_index("interactivee")
    # a submit typo is the same loud error, not a silent demotion
    records = []
    srv = _qos_engine(records)
    with pytest.raises(ValueError, match="unknown QoS tier"):
        srv.submit([1, 2, 3], tier="premium")
    assert records == [] and srv.queue_depth == 0


def test_qos_off_is_fifo():
    """Disabled QoS must schedule exactly as the pre-QoS engine: the
    selection is always the queue head, whatever tiers requests name."""
    records = []
    srv = _qos_engine(records, qos=QoSConfig(enabled=False), slots=1)
    rids = [
        srv.submit([1, 2, 3], tier=t)
        for t in ("best_effort", "batch", "interactive", "best_effort")
    ]
    while srv.queue_depth:
        assert srv._select_queued(time.perf_counter()) == 0
        srv.step()
    srv.run()
    for rec in records:
        assert rec["completion_reason"] in ("stop", "length")
    assert sorted(r["request_id"] for r in records) == sorted(rids)


# ---------------------------------------------------------------------------
# admission ordering: tier -> WFQ -> EDF -> FIFO, aging promotion
# ---------------------------------------------------------------------------


def test_tiered_admission_order_and_edf():
    records = []
    srv = _qos_engine(records)
    now = time.perf_counter()
    srv.submit([1, 2, 3], request_id="be", tenant="scraper", t_submit=now)
    srv.submit([1, 2, 3], request_id="b-late", tenant="ebatch",
               t_submit=now, deadline_s=100.0)
    srv.submit([1, 2, 3], request_id="b-soon", tenant="ebatch",
               t_submit=now + 0.001, deadline_s=5.0)
    srv.submit([1, 2, 3], request_id="i", tenant="chat", t_submit=now)
    q = list(srv._queue)
    # highest tier first, regardless of submission order
    assert q[srv._select_queued(now + 0.01)].rid == "i"
    srv._queue.remove(q[3])
    # within a tier: EDF beats FIFO (b-soon arrived later but is due first)
    q = list(srv._queue)
    assert q[srv._select_queued(now + 0.01)].rid == "b-soon"
    srv.run()  # drain so the engine ends idle


def test_wfq_least_normalized_service_wins():
    records = []
    qos = QoSConfig(enabled=True, tenants={
        "heavy": TenantConfig(tier="interactive", weight=2.0),
        "light": TenantConfig(tier="interactive", weight=1.0),
    })
    srv = _qos_engine(records, qos=qos)
    now = time.perf_counter()
    srv.submit([1, 2, 3], request_id="l", tenant="light", t_submit=now)
    srv.submit([1, 2, 3], request_id="h", tenant="heavy", t_submit=now + 0.001)
    # equal raw service 100: heavy's normalized share (100/2) is below
    # light's (100/1), so heavy is next despite submitting later
    srv._wfq_served[("interactive", "heavy")] = 100.0
    srv._wfq_served[("interactive", "light")] = 100.0
    q = list(srv._queue)
    assert q[srv._select_queued(now + 0.01)].rid == "h"
    srv.run()


def test_aging_promotes_to_top_tier():
    records = []
    srv = _qos_engine(records)
    now = time.perf_counter()
    # a best_effort request queued past aging_s orders as tier 0 — and
    # wins the FIFO tiebreak against fresh interactive work
    srv.submit([1, 2, 3], request_id="old-be", tenant="scraper",
               t_submit=now - srv.config.qos.aging_s - 1.0)
    srv.submit([1, 2, 3], request_id="i", tenant="chat", t_submit=now)
    old = next(q for q in srv._queue if q.rid == "old-be")
    assert old.tier_idx == 2
    assert srv._effective_tier(old, now) == 0
    q = list(srv._queue)
    assert q[srv._select_queued(now)].rid == "old-be"
    srv.run()


# ---------------------------------------------------------------------------
# quotas: token buckets, the retriable `quota` reason, the record seam
# ---------------------------------------------------------------------------


def test_quota_buckets_reject_and_refill():
    records = []
    qos = QoSConfig(enabled=True, tenants={
        "limited": TenantConfig(
            tier="interactive", requests_per_s=1.0, burst_s=1.0
        ),
        "decoder": TenantConfig(
            tier="batch", decode_tokens_per_s=8.0, burst_s=1.0
        ),
    })
    srv = _qos_engine(records, qos=qos)
    t0 = time.perf_counter()
    # admission bucket: capacity 1 -> second take at the same instant fails
    srv.submit([1, 2, 3], tenant="limited", t_submit=t0 - 10.0)
    with pytest.raises(QuotaExceeded) as ei:
        srv.submit([1, 2, 3], tenant="limited", t_submit=t0 - 10.0)
    assert ei.value.tenant == "limited" and ei.value.tier == "interactive"
    # submit raised RECORDLESS: retries must not inflate any counter
    assert srv.quota_total == 0 and records == []
    # 9s later the bucket refilled -> admitted again
    srv.submit([1, 2, 3], tenant="limited", t_submit=t0 - 1.0)
    # decode budget is charged worst-case (max_new) at admission
    srv.submit([1, 2], tenant="decoder", max_new_tokens=6,
               t_submit=t0 - 0.5)
    with pytest.raises(QuotaExceeded) as ei:
        srv.submit([1, 2], tenant="decoder", max_new_tokens=6,
                   t_submit=t0 - 0.49)
    assert ei.value.tenant == "decoder" and ei.value.tier == "batch"
    # the answering front gives up -> exactly one labeled quota record
    rec = srv.record_quota(
        request_id="gave-up", tenant="decoder", tier="batch"
    )
    assert rec["completion_reason"] == "quota" and rec["retriable"] is True
    assert rec["tenant"] == "decoder" and rec["tier"] == "batch"
    assert srv.quota_total == 1
    assert [r["request_id"] for r in records
            if r["completion_reason"] == "quota"] == ["gave-up"]
    srv.run()
    # the quota landed on /metrics: the plain counter and both labeled
    # families (quota is event-driven — sync() must not double it)
    srv.metrics.sync(srv)
    fams = parse_exposition(srv.metrics.registry.render())
    assert fams["automodel_serve_requests_quota"].samples[()] == 1.0
    assert fams["automodel_serve_tier_requests"].samples[
        (("reason", "quota"), ("tier", "batch"))
    ] == 1.0
    assert fams["automodel_serve_tenant_requests"].samples[
        (("reason", "quota"), ("tenant", "decoder"))
    ] == 1.0
    assert srv.qos_snapshot()["tenants"]["decoder"]["quota"] == 1


# ---------------------------------------------------------------------------
# overload shedding: strictly lowest-tier-first
# ---------------------------------------------------------------------------


def test_shed_lowest_tier_first_and_newcomer_refused():
    records = []
    srv = _qos_engine(records, max_queue=3)
    now = time.perf_counter()
    srv.submit([1, 2, 3], request_id="be-1", tenant="scraper", t_submit=now)
    srv.submit([1, 2, 3], request_id="b-1", tenant="ebatch",
               t_submit=now + 0.001)
    srv.submit([1, 2, 3], request_id="be-2", tenant="scraper",
               t_submit=now + 0.002)
    # full queue + higher-tier newcomer: the LATEST-submitted lowest-tier
    # entry is evicted with a terminal shed record (it was accepted — the
    # no-silent-drop contract owes it one)
    srv.submit([1, 2, 3], request_id="i-1", tenant="chat")
    assert srv.shed_total == 1
    shed = [r for r in records if r["completion_reason"] == "shed"]
    assert [r["request_id"] for r in shed] == ["be-2"]
    assert shed[0]["tier"] == "best_effort"
    assert shed[0]["tenant"] == "scraper"
    assert shed[0]["retriable"] is True
    rids = {q.rid for q in srv._queue}
    assert "i-1" in rids and "be-2" not in rids
    # equal tier is NOT strictly lower: a best_effort newcomer against a
    # queue whose worst entry is best_effort is itself refused, recordless
    with pytest.raises(QueueFull):
        srv.submit([1, 2, 3], request_id="be-3", tenant="scraper")
    assert srv.shed_total == 1 and len(records) == 1
    # batch newcomer still evicts the remaining best_effort entry
    srv.submit([1, 2, 3], request_id="b-2", tenant="ebatch")
    assert srv.shed_total == 2
    assert records[-1]["request_id"] == "be-1"
    assert records[-1]["tier"] == "best_effort"
    # nothing queued below batch -> a batch newcomer is refused
    with pytest.raises(QueueFull):
        srv.submit([1, 2, 3], request_id="b-3", tenant="ebatch")
    srv.run()


def test_record_shed_exactly_once_after_retries():
    """The record seam pin: a front absorbing backpressure by retrying
    submit() sees recordless QueueFull every time; only its final
    give-up (record_shed) produces the one tier-labeled record."""
    records = []
    srv = _qos_engine(records, max_queue=1)
    srv.submit([1, 2, 3], tenant="chat")
    for _ in range(3):  # the retrying front: 3 attempts, same tier
        with pytest.raises(QueueFull):
            srv.submit([1, 2, 3], tenant="chat")
    assert srv.shed_total == 0 and records == []
    rec = srv.record_shed(
        request_id="gave-up", tenant="scraper", tier="best_effort"
    )
    assert rec["completion_reason"] == "shed" and rec["retriable"] is True
    assert rec["tier"] == "best_effort" and rec["tenant"] == "scraper"
    assert srv.shed_total == 1
    assert len([r for r in records if r["completion_reason"] == "shed"]) == 1
    srv.run()
    srv.metrics.sync(srv)
    fams = parse_exposition(srv.metrics.registry.render())
    # ONE shed on every surface — not one per retry attempt
    assert fams["automodel_serve_requests_shed"].samples[()] == 1.0
    assert fams["automodel_serve_tier_requests"].samples[
        (("reason", "shed"), ("tier", "best_effort"))
    ] == 1.0


# ---------------------------------------------------------------------------
# drain: no tier jumps it
# ---------------------------------------------------------------------------


def test_drain_rejects_every_tier_and_flushes_queue_retriable():
    records = []
    srv = _qos_engine(records)
    accepted = [
        srv.submit([1, 2, 3], request_id="q-i", tenant="chat"),
        srv.submit([1, 2, 3], request_id="q-b", tenant="ebatch"),
    ]
    srv.begin_drain()
    # the draining check comes BEFORE any priority handling: the highest
    # tier is refused exactly like everything else, recordless
    with pytest.raises(EngineDraining):
        srv.submit([1, 2, 3], request_id="jumper", tenant="chat",
                   tier="interactive")
    assert records == []
    done = srv.step()
    drained = {r["request_id"]: r for r in done
               if r["completion_reason"] == "draining"}
    assert sorted(drained) == sorted(accepted)
    for rec in drained.values():
        assert rec["retriable"] is True
        assert rec["tier"] in TIERS and isinstance(rec["tenant"], str)
    # the refused submission never got a record anywhere
    assert all(r["request_id"] != "jumper" for r in records)
    assert srv.idle() and srv.drain_complete()


# ---------------------------------------------------------------------------
# overload e2e: ~2x capacity, Poisson, mixed tiers
# ---------------------------------------------------------------------------


def test_overload_poisson_mixed_tiers_sheds_lowest_first():
    records = []
    srv = _qos_engine(records, max_queue=6)
    rng = np.random.default_rng(20)
    tenants = ("chat", "ebatch", "scraper")
    tier_of = {"chat": "interactive", "ebatch": "batch",
               "scraper": "best_effort"}
    submitted, gave_up = {}, {}
    n_arr = 0
    i = 0
    while n_arr < 60 or not srv.idle():
        if n_arr < 60:
            # Poisson arrivals well past the 2-slot service rate: the
            # queue MUST overflow and the overflow must go downhill
            for _ in range(int(rng.poisson(2.0))):
                if n_arr >= 60:
                    break
                tenant = tenants[n_arr % 3]
                rid = f"req-{n_arr}-{tenant}"
                prompt = rng.integers(1, 64, size=int(rng.integers(2, 7)))
                try:
                    srv.submit(prompt.tolist(), request_id=rid, tenant=tenant)
                    submitted[rid] = tenant
                except QueueFull:
                    # the front gives up immediately: one shed record
                    srv.record_shed(request_id=rid, tenant=tenant,
                                    tier=tier_of[tenant])
                    gave_up[rid] = tenant
                n_arr += 1
        srv.step()
        srv.check_invariants()  # after EVERY scheduler event
        i += 1
        assert i < 100_000, "overload workload wedged"
    by_id = {r["request_id"]: r for r in records}
    # every request accounted exactly ONCE — accepted or refused
    assert len(records) == len(by_id)
    assert sorted(by_id) == sorted(set(submitted) | set(gave_up))
    # every terminal record carries its QoS labels
    for rec in records:
        assert rec["tier"] in TIERS, rec
        assert isinstance(rec["tenant"], str)
        if rec["completion_reason"] == "shed":
            assert rec["retriable"] is True
    # sheds went strictly downhill: per-tier shed fraction is monotone in
    # tier rank, and the protected tier completed at least as often as
    # the tier the fleet ranks last
    frac = {}
    for tenant in tenants:
        tier = tier_of[tenant]
        total = [r for r in by_id.values() if r["tenant"] == tenant]
        shed = [r for r in total if r["completion_reason"] == "shed"]
        comp = [r for r in total
                if r["completion_reason"] in ("stop", "length")]
        frac[tier] = (
            len(shed) / len(total), len(comp) / len(total), comp
        )
    assert frac["best_effort"][0] > 0, "overload never shed the bottom tier"
    assert frac["interactive"][0] <= frac["batch"][0] <= frac["best_effort"][0]
    assert frac["interactive"][1] >= frac["best_effort"][1]
    # the high tier held its latency: queue wait (the ttft component
    # admission control owns) stays at-or-below the bottom tier's
    i_wait = [r["queue_s"] for r in frac["interactive"][2]]
    be_wait = [r["queue_s"] for r in frac["best_effort"][2]]
    if len(i_wait) >= 3 and len(be_wait) >= 3:
        assert float(np.median(i_wait)) <= float(np.median(be_wait)) + 1e-9
    # the engine's own rollups agree with the records
    snap = srv.qos_snapshot()
    assert snap["enabled"] is True
    assert sum(c.get("completed", 0) for c in snap["tiers"].values()) == (
        srv.completed_total
    )
    assert srv.shed_total == sum(
        1 for r in records if r["completion_reason"] == "shed"
    )


# ---------------------------------------------------------------------------
# noisy neighbor: fault_injection serve_tenant_flood
# ---------------------------------------------------------------------------


def test_tenant_flood_quota_isolates_and_ages():
    records = []
    qos = QoSConfig(
        enabled=True, aging_s=0.3,
        tenants=_tenants(
            flood=TenantConfig(
                tier="best_effort", requests_per_s=5.0, burst_s=1.0
            ),
        ),
    )
    srv = _qos_engine(records, qos=qos)
    fi.activate({
        "serve_tenant_flood_at_step": 2,
        "serve_tenant_flood_requests": 12,
        "serve_tenant_flood_tenant": "flood",
    })
    demo = [
        srv.submit(
            rng_prompt.tolist(), request_id=f"demo-{i}", tenant="chat"
        )
        for i, rng_prompt in enumerate(
            np.random.default_rng(3).integers(1, 64, size=(6, 4))
        )
    ]
    aged_checked = False
    for i in range(100_000):
        if srv.idle():
            break
        srv.step()
        srv.check_invariants()  # after EVERY scheduler event
        flooded = [q for q in srv._queue if q.tenant == "flood"]
        if flooded and not aged_checked:
            # anti-starvation: once queued past aging_s the flood's
            # ADMITTED requests order as top tier — bounded delay, not
            # starvation, even while interactive traffic is live
            time.sleep(qos.aging_s + 0.05)
            now = time.perf_counter()
            assert srv._effective_tier(flooded[0], now) == 0
            aged_checked = True
    else:
        raise AssertionError("flood workload wedged")
    assert aged_checked, "flood requests never queued — injection missed"
    by_id = {r["request_id"]: r for r in records}
    assert len(by_id) == len(records), "a request got two terminal records"
    # the flood: every injected id accounted exactly once — admitted ones
    # completed, over-quota ones got ONE labeled quota record each
    flood_recs = {r for r in by_id if r.startswith("flood-")}
    assert len(flood_recs) == 12
    quota_recs = [r for r in records if r["completion_reason"] == "quota"]
    assert quota_recs and all(
        r["tenant"] == "flood" and r["tier"] == "best_effort"
        and r["retriable"] is True for r in quota_recs
    )
    admitted = [
        r for r in records
        if r["request_id"].startswith("flood-")
        and r["completion_reason"] in ("stop", "length")
    ]
    assert len(admitted) + len(quota_recs) == 12
    assert len(admitted) >= 1, "the whole flood was quota-rejected"
    assert srv.quota_total == len(quota_recs)
    # isolation: the victim tenant's work all completed, none shed
    for rid in demo:
        assert by_id[rid]["completion_reason"] in ("stop", "length")
    snap = srv.qos_snapshot()
    assert snap["tenants"]["flood"]["quota"] == len(quota_recs)
    srv.metrics.sync(srv)
    fams = parse_exposition(srv.metrics.registry.render())
    assert fams["automodel_serve_requests_quota"].samples[()] == float(
        len(quota_recs)
    )


# ---------------------------------------------------------------------------
# /metrics labels: engine scrape, federation rollup, per-tier SLO burn
# ---------------------------------------------------------------------------


def test_engine_scrape_carries_tier_and_tenant_labels():
    records = []
    srv = _qos_engine(records)
    srv.submit([1, 2, 3, 4], tenant="chat")
    srv.submit([2, 3, 4], tenant="ebatch")
    srv.run()
    assert srv.completed_total == 2
    srv.metrics.sync(srv)
    fams = parse_exposition(srv.metrics.registry.render())
    reasons = {r["completion_reason"] for r in records}
    for rec in records:
        key = (("reason", rec["completion_reason"]), ("tier", rec["tier"]))
        assert fams["automodel_serve_tier_requests"].samples[key] >= 1.0
        tkey = (
            ("reason", rec["completion_reason"]), ("tenant", rec["tenant"])
        )
        assert fams["automodel_serve_tenant_requests"].samples[tkey] >= 1.0
    assert reasons <= {"stop", "length"}
    # the per-tier ttft histogram — the per-tier SLO burn target
    hists = fams["automodel_serve_tier_ttft_seconds"].histograms
    assert hists[(("tier", "interactive"),)].count == 1
    assert hists[(("tier", "batch"),)].count == 1


def _replica_body(tier_ttft):
    """A replica /metrics body with the labeled QoS families populated:
    {tier: [ttft observations]} (one terminal per observation)."""
    reg = MetricsRegistry()
    tr = reg.labeled_counter(
        "automodel_serve_tier_requests", "by tier+reason", ("tier", "reason")
    )
    h = reg.labeled_histogram(
        "automodel_serve_tier_ttft_seconds", "ttft by tier", "tier",
        buckets=(0.05, 0.1, 0.5, 1.0),
    )
    reg.counter("automodel_serve_requests_completed", "done").inc(
        sum(len(v) for v in tier_ttft.values())
    )
    for tier, obs in tier_ttft.items():
        tr.inc((tier, "stop"), len(obs))
        for v in obs:
            h.observe(tier, v)
    return reg.render()


def test_federation_rolls_up_labeled_qos_families():
    fed = Federation(retention_s=120.0)
    fed.ingest("r0", _replica_body(
        {"interactive": [0.01, 0.02], "batch": [0.3]}
    ), now=1.0)
    fed.ingest("r1", _replica_body({"interactive": [0.04]}), now=1.0)
    fed.roll(1.0)
    # fleet aggregates keep the label tuples: one series per (tier, reason)
    fleet = fleet_name("automodel_serve_tier_requests")
    assert fed.latest(
        fleet, labels=(("reason", "stop"), ("tier", "interactive"))
    ) == 3.0
    assert fed.latest(
        fleet, labels=(("reason", "stop"), ("tier", "batch"))
    ) == 1.0
    # ingest a later sweep -> windowed increase per labeled series
    fed.ingest("r0", _replica_body(
        {"interactive": [0.01, 0.02, 0.03, 0.05], "batch": [0.3]}
    ), now=6.0)
    fed.ingest("r1", _replica_body({"interactive": [0.04]}), now=6.0)
    fed.roll(6.0)
    assert fed.increase(
        fleet, 10.0, 6.0, labels=(("reason", "stop"), ("tier", "interactive"))
    ) == 2.0
    hist = fed.histogram_increase(
        fleet_name("automodel_serve_tier_ttft_seconds"), 10.0, 6.0,
        labels=(("tier", "interactive"),),
    )
    assert hist is not None and hist.count == 2.0
    # the re-export round-trips: the federated body parses back with the
    # labeled fleet families AND the replica-labeled originals intact
    fams = parse_exposition(fed.render_federated())
    assert fams[fleet].samples[
        (("reason", "stop"), ("tier", "interactive"))
    ] == 5.0
    assert fams["automodel_serve_tier_requests"].samples[
        (("reason", "stop"), ("replica", "r1"), ("tier", "interactive"))
    ] == 1.0


class _SLOHarness:
    """SLO engine + federation with an injected scripted clock (the
    test_slo.py harness, fed the labeled tier histogram)."""

    def __init__(self, cfg):
        from automodel_tpu.telemetry.slo import SLOEngine

        self.fed = Federation(retention_s=cfg.retention_s)
        self.registry = MetricsRegistry()
        self.events = []
        self.now = 0.0
        self.engine = SLOEngine(
            cfg, self.fed, registry=self.registry,
            emit=self.events.append, wall=lambda: self.now,
        )

    def step(self, now, tier_ttft):
        self.now = now
        self.fed.ingest("r0", _replica_body(tier_ttft), now=now)
        self.fed.roll(now)
        self.engine.evaluate(now)


def test_per_tier_slo_burn_alert_fires_on_the_labeled_child():
    """The labels: selector judges ONE labeled child of the tier ttft
    histogram: an interactive regression fires even while the unlabeled
    traffic mix looks healthy, and slow batch traffic alone cannot."""
    from automodel_tpu.telemetry.slo import SLOConfig

    cfg = SLOConfig.from_dict({
        "fast_window_s": 10.0, "slow_window_s": 30.0,
        "for_s": 0.0, "resolve_s": 10.0,
        "objectives": [
            {"name": "ttft_p50_interactive", "kind": "latency",
             "metric": "automodel_serve_tier_ttft_seconds",
             "labels": {"tier": "interactive"},
             "q": 0.5, "threshold_s": 0.2},
            {"name": "ttft_p50_batch", "kind": "latency",
             "metric": "automodel_serve_tier_ttft_seconds",
             "labels": {"tier": "batch"},
             "q": 0.5, "threshold_s": 0.2},
        ],
    })
    assert cfg.objectives[0].labels == (("tier", "interactive"),)
    good, bad = [0.01], [0.7]
    h = _SLOHarness(cfg)
    # healthy warm-up in both windows, both tiers
    h.step(0.0, {"interactive": good * 5, "batch": good * 5})
    h.step(5.0, {"interactive": good * 10, "batch": good * 10})
    # the interactive child degrades; batch stays fast. Cumulative bodies:
    # 40 of interactive's fast-window observations are over threshold
    h.step(10.0, {"interactive": good * 10 + bad * 40,
                  "batch": good * 50})
    assert h.engine.firing() == ["ttft_p50_interactive"]
    ev = [e for e in h.events if e["state"] == "firing"]
    assert len(ev) == 1 and ev[0]["slo"] == "ttft_p50_interactive"
    assert ev[0]["slo_value"] > 0.2
    snap = h.engine.snapshot()
    assert snap["ttft_p50_batch"]["state"] == "ok"
    # the mirror case: only batch burning never pages the interactive SLO
    h2 = _SLOHarness(cfg)
    h2.step(0.0, {"interactive": good * 5, "batch": good * 5})
    h2.step(5.0, {"interactive": good * 10, "batch": good * 10})
    h2.step(10.0, {"interactive": good * 50,
                   "batch": good * 10 + bad * 40})
    assert h2.engine.firing() == ["ttft_p50_batch"]
    assert h2.engine.snapshot()["ttft_p50_interactive"]["state"] == "ok"


# ---------------------------------------------------------------------------
# fleet: router helpers, aggregate_qos, fleet-status TIER/TENANT tables
# ---------------------------------------------------------------------------


def test_router_tier_helpers_and_retry_after_scaling():
    from automodel_tpu.serving.fleet.router import (
        RETRY_AFTER_S,
        _tier_label,
        _tier_retry_after,
    )
    from automodel_tpu.serving.server import (
        _tier_retry_after as server_retry_after,
    )

    # arbitrary client strings must not mint unbounded label values
    assert _tier_label("interactive") == "interactive"
    assert _tier_label("premium<script>") == "interactive"
    assert _tier_label(None) == "interactive"
    # Retry-After goes uphill: lower tiers back off longer, and the
    # router's jax-free mirror agrees with the serving front's
    advice = [_tier_retry_after(t) for t in TIERS]
    assert advice == [RETRY_AFTER_S, 2 * RETRY_AFTER_S, 3 * RETRY_AFTER_S]
    assert [server_retry_after(t) for t in TIERS] == advice
    assert server_retry_after("garbage") == RETRY_AFTER_S


def test_aggregate_qos_sums_replica_snapshots():
    from automodel_tpu.serving.fleet.router import aggregate_qos

    s0 = {
        "enabled": True,
        "queued_by_tier": {"interactive": 2, "batch": 1, "best_effort": 0},
        "queued_by_tenant": {"chat": 2, "ebatch": 1},
        "tiers": {"interactive": {"completed": 5, "shed": 0, "timeout": 0,
                                  "quota": 0}},
        "tenants": {"chat": {"requests": 5, "completed": 5, "shed": 0,
                             "quota": 0, "timeout": 0}},
    }
    s1 = {
        "enabled": True,
        "queued_by_tier": {"interactive": 1, "batch": 0, "best_effort": 3},
        "queued_by_tenant": {"chat": 1, "scraper": 3},
        "tiers": {"interactive": {"completed": 2, "shed": 1, "timeout": 0,
                                  "quota": 0},
                  "best_effort": {"completed": 0, "shed": 4, "timeout": 0,
                                  "quota": 2}},
        "tenants": {"chat": {"requests": 3, "completed": 2, "shed": 1,
                             "quota": 0, "timeout": 0}},
    }
    agg = aggregate_qos([s0, None, "junk", s1])
    assert agg["enabled"] is True
    assert agg["queued_by_tier"]["interactive"] == 3
    assert agg["queued_by_tier"]["best_effort"] == 3
    assert agg["queued_by_tenant"] == {"chat": 3, "ebatch": 1, "scraper": 3}
    assert agg["tiers"]["interactive"]["completed"] == 7
    assert agg["tiers"]["interactive"]["shed"] == 1
    assert agg["tiers"]["best_effort"]["quota"] == 2
    assert agg["tenants"]["chat"]["requests"] == 8
    # all replicas disabled (or no qos block at all) -> disabled rollup
    assert aggregate_qos([{"enabled": False}, {}])["enabled"] is False


def test_fleet_status_renders_tier_and_tenant_tables():
    from automodel_tpu.serving.fleet.status import (
        qos_summary_lines,
        render_table,
    )

    stats = {
        "replicas": {
            "r0": {"role": "mixed", "ready": True, "alive": True,
                   "queue_depth": 1, "busy_slots": 2,
                   "block_occupancy": 0.5},
        },
        "replicas_ready": 1,
        "qos": {
            "enabled": True,
            "queued_by_tier": {"interactive": 2, "batch": 0,
                               "best_effort": 5},
            "queued_by_tenant": {"chat": 2, "scraper": 5},
            "tiers": {
                "interactive": {"completed": 9, "shed": 0, "timeout": 0,
                                "quota": 0},
                "best_effort": {"completed": 1, "shed": 7, "timeout": 1,
                                "quota": 3},
            },
            "tenants": {
                "chat": {"requests": 9, "completed": 9, "shed": 0,
                         "quota": 0, "timeout": 0},
                "scraper": {"requests": 12, "completed": 1, "shed": 7,
                            "quota": 3, "timeout": 1},
            },
        },
    }
    lines = qos_summary_lines(stats)
    text = "\n".join(lines)
    assert "QoS tiers:" in text and "QoS tenants" in text
    # every tier is a row (zero rows included), columns carry the numbers
    for tier in TIERS:
        assert any(line.strip().startswith(tier) for line in lines), tier
    be_row = next(l for l in lines if l.strip().startswith("best_effort"))
    assert be_row.split() == ["best_effort", "5", "1", "7", "3", "1"]
    scraper_row = next(l for l in lines if l.strip().startswith("scraper"))
    assert scraper_row.split() == ["scraper", "5", "1", "7", "3", "1"]
    # the full table embeds the block; disabled QoS leaves it untouched
    assert "QoS tiers:" in render_table(stats)
    assert qos_summary_lines({"qos": {"enabled": False}}) == []
    assert qos_summary_lines({}) == []


# ---------------------------------------------------------------------------
# report: per-tier histograms in the summary, label lint
# ---------------------------------------------------------------------------


def test_report_summarizes_per_tier_sheds_and_lints_labels(tmp_path):
    from automodel_tpu.telemetry.report import (
        lint_metrics_jsonl,
        summarize_metrics,
    )

    records = []
    srv = _qos_engine(records, max_queue=2)
    srv.submit([1, 2, 3], request_id="be-1", tenant="scraper")
    srv.submit([1, 2, 3], request_id="be-2", tenant="scraper")
    srv.submit([1, 2, 3], request_id="i-1", tenant="chat")  # evicts be-2
    srv.record_quota(request_id="q-1", tenant="scraper", tier="best_effort")
    srv.run()
    path = tmp_path / "m.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    recs, problems = lint_metrics_jsonl(str(path))
    assert problems == []
    summary = summarize_metrics(recs)
    assert summary["serve_shed"] == 1
    assert summary["serve_quota"] == 1
    assert summary["serve_shed_by_tier"] == {"best_effort": 1}
    assert summary["serve_quota_by_tenant"] == {"scraper": 1}
    assert "serve_timeouts_by_tier" not in summary  # nothing timed out
    # a non-string QoS label is a foreign writer: report --strict flags it
    bad = dict(records[-1])
    bad["tenant"] = 123
    path.write_text(json.dumps(bad) + "\n")
    _, problems = lint_metrics_jsonl(str(path))
    assert any("tenant" in p for p in problems)


# ---------------------------------------------------------------------------
# docs drift guard: every emittable reason is in the runbook table
# ---------------------------------------------------------------------------


def test_every_completion_reason_documented_in_serving_runbook():
    """docs/serving.md's reason table must name every reason the engine
    can stamp on a terminal record — `quota` included. A new reason that
    ships without its runbook row fails here, not in an operator's
    incident."""
    text = (DOCS / "serving.md").read_text()
    m = re.search(
        r"^\| reason \|.*?\n\|[-| ]+\|\n(.*?)\n\n",
        text, re.M | re.S,
    )
    assert m, "docs/serving.md lost its completion_reason runbook table"
    documented = set()
    for row in m.group(1).splitlines():
        first_cell = row.split("|")[1] if row.count("|") >= 2 else ""
        documented.update(re.findall(r"`([a-z_]+)`", first_cell))
    missing = [r for r in COMPLETION_REASONS if r not in documented]
    assert not missing, (
        "engine completion_reasons absent from the docs/serving.md "
        f"runbook table: {missing}"
    )
    # the glossary side: the QoS label names and counters are documented
    obs = (DOCS / "observability.md").read_text()
    for needle in (
        "`tenant`", "`tier`", "automodel_serve_requests_quota",
        "automodel_serve_tier_requests", "automodel_serve_tenant_requests",
        "automodel_serve_tier_ttft_seconds",
        "automodel_route_tier_requests",
    ):
        assert needle in obs, f"docs/observability.md lost {needle}"
