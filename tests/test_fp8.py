"""FP8 training path (reference quantization/fp8.py + te_fp8 recipes):
e4m3-forward / e5m2-gradient matmuls with per-tensor dynamic scaling."""

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.ops.fp8 import fp8_dot


def test_fp8_dot_value_close():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 32)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    ref = np.asarray(x @ w)
    out = np.asarray(fp8_dot(x, w))
    # e4m3 ~ 3 mantissa bits after per-tensor scaling
    denom = np.abs(ref).max()
    assert np.abs(out - ref).max() / denom < 0.12


def test_fp8_dot_grads_flow():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)

    def loss(x, w):
        return (fp8_dot(x, w) ** 2).sum()

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(lambda x, w: ((x @ w) ** 2).sum(), argnums=(0, 1))(x, w)
    for g, r in ((gx, rx), (gw, rw)):
        denom = np.abs(np.asarray(r)).max()
        assert np.abs(np.asarray(g) - np.asarray(r)).max() / denom < 0.25
        assert np.isfinite(np.asarray(g)).all()


def test_llama_trains_with_fp8(devices8):
    from automodel_tpu import auto_model
    from automodel_tpu.data.loader import place_batch
    from automodel_tpu.optim.builders import build_optimizer
    from automodel_tpu.parallel.mesh import MeshConfig, build_mesh
    from automodel_tpu.training.train_state import TrainState
    from automodel_tpu.training.train_step import build_train_step, make_causal_lm_loss

    hf = {
        "architectures": ["LlamaForCausalLM"], "model_type": "llama",
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 2,
        "num_key_value_heads": 1, "head_dim": 16,
    }
    ctx = build_mesh(MeshConfig(dp_shard=8), devices=devices8)
    auto = auto_model.from_config(
        hf, ctx,
        {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32",
         "fp8": True},
        seed=0,
    )
    opt = build_optimizer(name="adamw", lr=5e-3, grad_clip_norm=1.0)
    state = TrainState.create(auto.params, jax.jit(opt.init)(auto.params))
    step = build_train_step(
        make_causal_lm_loss(auto.model, constrain=auto.constrain), opt
    )
    ids = np.random.default_rng(0).integers(0, 64, size=(1, 8, 16)).astype(np.int32)
    batch = place_batch(ctx, {"input_ids": ids, "labels": ids})
    losses = []
    for _ in range(4):
        state, m = step(state, batch)
        losses.append(float(jax.device_get(m["loss"])))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_fp8_experts_qdq_blockwise():
    """Blockwise e4m3 QDQ: ≤256 distinct levels per 128x128 block, STE
    identity gradient, and error bounded by the block absmax/448 step."""
    import numpy as np
    from automodel_tpu.ops.fp8 import fp8_qdq_blockwise, fp8_qdq_tensor

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(4, 200, 300)), jnp.float32)  # non-divisible dims
    q = fp8_qdq_blockwise(w, block=128)
    assert q.shape == w.shape and q.dtype == w.dtype
    err = float(jnp.abs(q - w).max())
    assert 0 < err < 0.2 * float(jnp.abs(w).max())
    g = jax.grad(lambda w: fp8_qdq_blockwise(w).sum())(w)
    np.testing.assert_array_equal(np.asarray(g), np.ones_like(np.asarray(g)))
    x = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    g = jax.grad(lambda x: fp8_qdq_tensor(x).sum())(x)
    np.testing.assert_array_equal(np.asarray(g), np.ones_like(np.asarray(g)))


def test_fp8_experts_path_close_to_bf16():
    """ragged experts with fp8=True stays close to the exact path and trains
    (reference GroupedExpertsFP8 tolerance-level parity)."""
    import numpy as np
    from automodel_tpu.moe.config import MoEConfig
    from automodel_tpu.moe.experts import ragged_experts
    from automodel_tpu.moe.gate import gate

    rng = np.random.default_rng(1)
    T, D, E, I, K = 48, 32, 4, 24, 2
    cfg = MoEConfig(num_experts=E, num_experts_per_tok=K,
                    moe_intermediate_size=I, norm_topk_prob=True)
    x = jnp.asarray(rng.normal(size=(T, D)), jnp.float32)
    router = jnp.asarray(rng.normal(size=(D, E)), jnp.float32) * 0.1
    weights = {
        "gate_up": jnp.asarray(rng.normal(size=(E, D, 2 * I)), jnp.float32) * 0.1,
        "down": jnp.asarray(rng.normal(size=(E, I, D)), jnp.float32) * 0.1,
    }
    gout = gate(x, router, cfg)
    act2 = lambda g, u: jax.nn.silu(g) * u
    exact = ragged_experts(x, gout, weights, cfg, act2)
    fp8 = ragged_experts(x, gout, weights, cfg, act2, fp8=True)
    rel = float(jnp.abs(fp8 - exact).max() / (jnp.abs(exact).max() + 1e-9))
    assert 0 < rel < 0.1, rel
    # gradients flow to weights through the QDQ (STE)
    gw = jax.grad(
        lambda w: ragged_experts(x, gout, w, cfg, act2, fp8=True).sum()
    )(weights)
    assert float(jnp.abs(gw["gate_up"]).max()) > 0
