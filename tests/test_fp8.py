"""FP8 training path (reference quantization/fp8.py + te_fp8 recipes):
e4m3-forward / e5m2-gradient matmuls with per-tensor dynamic scaling."""

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.ops.fp8 import fp8_dot


def test_fp8_dot_value_close():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 32)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    ref = np.asarray(x @ w)
    out = np.asarray(fp8_dot(x, w))
    # e4m3 ~ 3 mantissa bits after per-tensor scaling
    denom = np.abs(ref).max()
    assert np.abs(out - ref).max() / denom < 0.12


def test_fp8_dot_grads_flow():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)

    def loss(x, w):
        return (fp8_dot(x, w) ** 2).sum()

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(lambda x, w: ((x @ w) ** 2).sum(), argnums=(0, 1))(x, w)
    for g, r in ((gx, rx), (gw, rw)):
        denom = np.abs(np.asarray(r)).max()
        assert np.abs(np.asarray(g) - np.asarray(r)).max() / denom < 0.25
        assert np.isfinite(np.asarray(g)).all()


def test_llama_trains_with_fp8(devices8):
    from automodel_tpu import auto_model
    from automodel_tpu.data.loader import place_batch
    from automodel_tpu.optim.builders import build_optimizer
    from automodel_tpu.parallel.mesh import MeshConfig, build_mesh
    from automodel_tpu.training.train_state import TrainState
    from automodel_tpu.training.train_step import build_train_step, make_causal_lm_loss

    hf = {
        "architectures": ["LlamaForCausalLM"], "model_type": "llama",
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 2,
        "num_key_value_heads": 1, "head_dim": 16,
    }
    ctx = build_mesh(MeshConfig(dp_shard=8), devices=devices8)
    auto = auto_model.from_config(
        hf, ctx,
        {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32",
         "fp8": True},
        seed=0,
    )
    opt = build_optimizer(name="adamw", lr=5e-3, grad_clip_norm=1.0)
    state = TrainState.create(auto.params, jax.jit(opt.init)(auto.params))
    step = build_train_step(
        make_causal_lm_loss(auto.model, constrain=auto.constrain), opt
    )
    ids = np.random.default_rng(0).integers(0, 64, size=(1, 8, 16)).astype(np.int32)
    batch = place_batch(ctx, {"input_ids": ids, "labels": ids})
    losses = []
    for _ in range(4):
        state, m = step(state, batch)
        losses.append(float(jax.device_get(m["loss"])))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
