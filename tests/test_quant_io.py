"""Quantized-checkpoint ingest tests (checkpoint/quant_io.py).

Parity targets: reference models/deepseek_v3/state_dict_adapter.py:375
(FP8-blockwise dequant) and models/gpt_oss/state_dict_adapter.py:117
(MXFP4 unpack) — here exercised through synthetic quantize→write→read
round trips against the transparent reader hook."""

import numpy as np
import ml_dtypes
import pytest

from automodel_tpu.checkpoint import quant_io
from automodel_tpu.checkpoint.hf_io import HFCheckpointReader, save_hf_checkpoint


def test_fp8_blockwise_roundtrip():
    rng = np.random.default_rng(0)
    # deliberately non-multiple of 128 in both dims to cover edge blocks
    w = rng.standard_normal((200, 300)).astype(np.float32)
    q, scale_inv = quant_io.quantize_fp8_blockwise(w)
    assert q.dtype == np.dtype(ml_dtypes.float8_e4m3fn)
    assert scale_inv.shape == (2, 3)
    deq = quant_io.dequantize_fp8_blockwise(q, scale_inv, dtype=np.float32)
    # e4m3 has ~2 mantissa bits of headroom after per-block scaling
    assert np.max(np.abs(deq - w)) / np.max(np.abs(w)) < 0.07


def test_fp8_exact_for_representable_values():
    # values exactly representable in e4m3 with scale 1 round-trip bit-exactly
    w = np.array([[0.5, 1.0, -2.0], [4.0, 0.25, -0.125]], np.float32)
    q = w.astype(ml_dtypes.float8_e4m3fn)
    scale_inv = np.ones((1, 1), np.float32)
    deq = quant_io.dequantize_fp8_blockwise(q, scale_inv, dtype=np.float32)
    np.testing.assert_array_equal(deq, w)


def test_mxfp4_roundtrip_exact():
    rng = np.random.default_rng(1)
    # compose from exactly-representable e2m1 mantissas x power-of-two scales
    codes = rng.integers(0, 16, size=(3, 8, 64))
    mant = quant_io.FP4_VALUES[codes]
    exp = rng.integers(-3, 4, size=(3, 8, 64 // 32))
    w_rt = mant.reshape(3, 8, 2, 32) * np.exp2(exp)[..., None]
    w = np.swapaxes(w_rt.reshape(3, 8, 64), -1, -2).astype(ml_dtypes.bfloat16)
    blocks, scales = quant_io.pack_mxfp4(w)
    assert blocks.shape == (3, 8, 2, 16)
    assert scales.shape == (3, 8, 2)
    deq = quant_io.dequantize_mxfp4(blocks, scales)
    np.testing.assert_array_equal(np.asarray(deq, np.float32), np.asarray(w, np.float32))


def test_mxfp4_quantization_error_bounded():
    rng = np.random.default_rng(2)
    w = rng.standard_normal((4, 96, 16)).astype(np.float32)
    blocks, scales = quant_io.pack_mxfp4(w)
    deq = np.asarray(quant_io.dequantize_mxfp4(blocks, scales), np.float32)
    # e2m1 with shared e8m0 scale: worst case is half the 4→6 code gap at a
    # doubled (rounded-up power-of-two) scale → |err| <= absmax/3 per group
    grp = np.swapaxes(w, -1, -2).reshape(4, 16, 3, 32)
    dq = np.swapaxes(deq, -1, -2).reshape(4, 16, 3, 32)
    absmax = np.abs(grp).max(-1, keepdims=True)
    assert np.max(np.abs(dq - grp) / np.maximum(absmax, 1e-6)) < 0.34


def test_reader_transparent_fp8(tmp_path):
    rng = np.random.default_rng(3)
    w = rng.standard_normal((160, 130)).astype(np.float32)
    q, scale_inv = quant_io.quantize_fp8_blockwise(w)
    plain = rng.standard_normal((8, 8)).astype(ml_dtypes.bfloat16)
    save_hf_checkpoint(
        tmp_path,
        [("blk.weight", q), ("blk.weight_scale_inv", scale_inv), ("norm.weight", plain)],
    )
    r = HFCheckpointReader(tmp_path)
    assert sorted(r.keys()) == ["blk.weight", "norm.weight"]
    assert r.info("blk.weight") == ("BF16", (160, 130))
    deq = r.get_tensor("blk.weight")
    assert deq.dtype == np.dtype(ml_dtypes.bfloat16)
    assert np.max(np.abs(deq.astype(np.float32) - w)) / np.abs(w).max() < 0.1
    np.testing.assert_array_equal(r.get_tensor("norm.weight"), plain)
    # raw mode exposes the quantized payloads untouched
    raw = HFCheckpointReader(tmp_path, dequantize=False)
    assert sorted(raw.keys()) == ["blk.weight", "blk.weight_scale_inv", "norm.weight"]
    assert raw.get_tensor("blk.weight").dtype == np.dtype(ml_dtypes.float8_e4m3fn)
    r.close()
    raw.close()


def test_reader_transparent_mxfp4(tmp_path):
    rng = np.random.default_rng(4)
    codes = rng.integers(0, 16, size=(2, 6, 64))
    w_rt = quant_io.FP4_VALUES[codes].reshape(2, 6, 2, 32) * np.exp2(
        rng.integers(-2, 3, size=(2, 6, 2))
    )[..., None]
    w = np.swapaxes(w_rt.reshape(2, 6, 64), -1, -2).astype(ml_dtypes.bfloat16)
    blocks, scales = quant_io.pack_mxfp4(w)
    save_hf_checkpoint(
        tmp_path,
        [
            ("mlp.experts.gate_up_proj_blocks", blocks),
            ("mlp.experts.gate_up_proj_scales", scales),
        ],
    )
    r = HFCheckpointReader(tmp_path)
    assert r.keys() == ["mlp.experts.gate_up_proj"]
    assert r.info("mlp.experts.gate_up_proj") == ("BF16", (2, 64, 6))
    deq = r.get_tensor("mlp.experts.gate_up_proj")
    np.testing.assert_array_equal(
        np.asarray(deq, np.float32), np.asarray(w, np.float32)
    )
    r.close()


def test_gpt_oss_adapter_loads_mxfp4_checkpoint(tmp_path):
    """End-to-end: a synthetic MXFP4 GPT-OSS checkpoint loads through the
    unmodified state-dict adapter (the reader dequantizes underneath)."""
    import jax

    from automodel_tpu.models.gpt_oss.model import GptOssConfig, GptOssForCausalLM
    from automodel_tpu.models.gpt_oss.state_dict_adapter import GptOssStateDictAdapter

    cfg = GptOssConfig.from_hf(
        {
            "model_type": "gpt_oss",
            "vocab_size": 64,
            "hidden_size": 32,
            "intermediate_size": 32,
            "num_hidden_layers": 1,
            "num_attention_heads": 2,
            "num_key_value_heads": 1,
            "head_dim": 16,
            "num_local_experts": 2,
            "num_experts_per_tok": 1,
            "sliding_window": 8,
        }
    )
    adapter = GptOssStateDictAdapter(cfg)
    params = GptOssForCausalLM(cfg).init(jax.random.key(0))
    tensors = {k: np.asarray(v) for k, v in adapter.to_hf(params)}

    # re-pack the two stacked expert tensors as MXFP4 (what the hub ships)
    originals = {}
    for name in ["gate_up_proj", "down_proj"]:
        key = f"model.layers.0.mlp.experts.{name}"
        originals[key] = tensors[key].astype(np.float32)
        blocks, scales = quant_io.pack_mxfp4(tensors.pop(key))
        tensors[f"{key}_blocks"] = blocks
        tensors[f"{key}_scales"] = scales
    save_hf_checkpoint(tmp_path, list(tensors.items()))

    r = HFCheckpointReader(tmp_path)
    loaded = adapter.from_hf(r.get_tensor)
    r.close()
    from automodel_tpu.models.gpt_oss.state_dict_adapter import _deint

    gate_up = np.asarray(loaded["layers"]["moe"]["experts"]["gate_up"], np.float32)
    # the adapter de-interleaves HF's gate_up at the boundary
    ref = _deint(originals["model.layers.0.mlp.experts.gate_up_proj"])
    assert gate_up.shape[1:] == ref.shape  # [L=1, ...] stacking on top
    scale = max(np.abs(ref).max(), 1e-6)
    assert np.max(np.abs(gate_up[0] - ref)) / scale < 0.2
