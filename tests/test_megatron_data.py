"""Megatron-style data pipeline: indexed datasets, native index builders,
GPT dataset, blending, nanoGPT shards, and an e2e pretrain step."""

import numpy as np
import pytest

from automodel_tpu.data.megatron.gpt_dataset import (
    BlendedDataset,
    GPTDataset,
    MegatronPretraining,
)
from automodel_tpu.data.megatron.helpers import (
    _build_sample_idx_py,
    _load,
    build_blending_indices,
    build_sample_idx,
)
from automodel_tpu.data.megatron.indexed_dataset import (
    IndexedDataset,
    IndexedDatasetWriter,
)


@pytest.fixture()
def corpus(tmp_path):
    rng = np.random.default_rng(0)
    with IndexedDatasetWriter(tmp_path / "corpus", dtype=np.uint16) as w:
        for _ in range(50):
            w.add_document(rng.integers(0, 1000, size=rng.integers(5, 120)))
    return tmp_path / "corpus"


def test_indexed_roundtrip(corpus):
    ds = IndexedDataset(corpus)
    assert len(ds) == 50
    assert ds.dtype == np.uint16
    assert ds.num_tokens == int(ds.sizes.sum())
    d0 = ds[0]
    assert len(d0) == ds.sizes[0]
    np.testing.assert_array_equal(ds.get_slice(3, 2, 3), ds[3][2:5])


def test_native_helpers_compiled_and_match_python():
    assert _load() is not None, "C++ helpers failed to compile"
    rng = np.random.default_rng(1)
    sizes = rng.integers(3, 50, size=40).astype(np.int32)
    doc_idx = np.tile(np.arange(40, dtype=np.int64), 4)
    rng.shuffle(doc_idx)
    native = build_sample_idx(sizes, doc_idx, 64, 20)
    py = _build_sample_idx_py(sizes, doc_idx, 64, 20)
    np.testing.assert_array_equal(native, py)


def test_sample_idx_exhaustion_raises():
    sizes = np.asarray([10], np.int32)
    with pytest.raises(ValueError, match="exhaust"):
        build_sample_idx(sizes, np.zeros(1, np.int64), 64, 5)


def test_blending_proportions():
    d_idx, s_idx = build_blending_indices(np.asarray([0.7, 0.2, 0.1]), 1000)
    counts = np.bincount(d_idx, minlength=3)
    np.testing.assert_allclose(counts / 1000, [0.7, 0.2, 0.1], atol=0.01)
    # per-dataset sample indices are sequential
    for d in range(3):
        np.testing.assert_array_equal(
            s_idx[d_idx == d], np.arange(counts[d])
        )


def test_gpt_dataset_samples(corpus):
    ds = GPTDataset(str(corpus), seq_length=32, num_samples=40, seed=0)
    assert len(ds) == 40
    ex = ds[0]
    assert ex["input_ids"].shape == (32,) and ex["labels"].shape == (32,)
    # next-token alignment inside the window
    np.testing.assert_array_equal(ex["input_ids"][1:], ex["labels"][:-1])
    # determinism
    ds2 = GPTDataset(str(corpus), seq_length=32, num_samples=40, seed=0)
    np.testing.assert_array_equal(ds[7]["input_ids"], ds2[7]["input_ids"])


def test_blended_and_wrapper(corpus, tmp_path):
    rng = np.random.default_rng(2)
    with IndexedDatasetWriter(tmp_path / "c2", dtype=np.uint16) as w:
        for _ in range(20):
            w.add_document(rng.integers(0, 1000, size=60))
    mp = MegatronPretraining(
        [str(corpus), str(tmp_path / "c2")], seq_length=16,
        num_samples=30, weights=[0.5, 0.5],
    )
    assert len(mp) == 30
    assert mp[0]["input_ids"].shape == (16,)


def test_nanogpt_dataset(tmp_path):
    from automodel_tpu.data.nanogpt import NanogptDataset

    tokens = np.arange(1000, dtype=np.uint16)
    (tmp_path / "shard0.bin").write_bytes(tokens.tobytes())
    ds = NanogptDataset(tmp_path, seq_length=64)
    assert len(ds) > 0
    ex = ds[1]
    np.testing.assert_array_equal(ex["input_ids"][1:], ex["labels"][:-1])
    assert ex["input_ids"][0] == 64  # stride = seq_length


def _nanogpt_sources(tmp_path):
    """Two sources, the first split over TWO .bin shards (so a stream
    crosses a real shard boundary), disjoint token ranges so every window
    names its origin."""
    a = tmp_path / "src_a"
    b = tmp_path / "src_b"
    a.mkdir(), b.mkdir()
    (a / "s0.bin").write_bytes(np.arange(0, 200, dtype=np.uint16).tobytes())
    (a / "s1.bin").write_bytes(np.arange(200, 400, dtype=np.uint16).tobytes())
    (b / "s0.bin").write_bytes(np.arange(5000, 5600, dtype=np.uint16).tobytes())
    return a, b


def test_blended_nanogpt_deterministic_and_weighted(tmp_path):
    from automodel_tpu.data.nanogpt import BlendedNanogptDataset

    a, b = _nanogpt_sources(tmp_path)
    sources = [{"paths": str(a), "weight": 1.0}, {"paths": str(b), "weight": 3.0}]
    ds = BlendedNanogptDataset(sources, seq_length=16, seed=5, num_samples=80)
    ds2 = BlendedNanogptDataset(sources, seq_length=16, seed=5, num_samples=80)
    # pure random access: any index re-derives the identical window
    for i in (0, 7, 41, 79):
        np.testing.assert_array_equal(ds[i]["input_ids"], ds2[i]["input_ids"])
        np.testing.assert_array_equal(
            ds[i]["input_ids"][1:], ds[i]["labels"][:-1]
        )
    counts = ds.source_counts()
    assert sum(counts) == 80
    assert counts[1] > counts[0]  # 3:1 blend favors source b
    # windows come from the claimed source (disjoint token ranges)
    for i in range(80):
        tok = int(ds[i]["input_ids"][0])
        src = 0 if tok < 400 else 1
        assert src == int(ds._assignment[i])
    # a windowless source must fail AT INIT, not at the arbitrary
    # mid-training step whose schedule slot first lands on it
    (tmp_path / "tiny").mkdir()
    (tmp_path / "tiny" / "s.bin").write_bytes(
        np.arange(4, dtype=np.uint16).tobytes()
    )
    import pytest as _pytest

    with _pytest.raises(ValueError, match="zero windows"):
        BlendedNanogptDataset(
            [{"paths": str(a)}, {"paths": str(tmp_path / "tiny")}],
            seq_length=16, seed=5, num_samples=10,
        )
    # a short source wraps with a fresh per-pass permutation, not a replay
    long = BlendedNanogptDataset(
        [{"paths": str(a)}], seq_length=16, seed=5, num_samples=60
    )
    n = len(long.datasets[0])
    pass0 = [int(long[i]["input_ids"][0]) for i in range(n)]
    pass1 = [int(long[i]["input_ids"][0]) for i in range(n, 2 * n)]
    assert sorted(pass0) == sorted(pass1) and pass0 != pass1


def test_blended_nanogpt_resume_mid_stream_across_shard_boundary(tmp_path):
    """ROADMAP 4c's resume contract, integrated with the PR 3 rollback
    fast-forward and the prefetch pipeline: consume a few groups, roll back
    to the last checkpointed cursor and fast-forward past the offending
    window (crossing both a .bin shard boundary and a source boundary), and
    require the continuation to equal an uninterrupted run's suffix —
    every window consumed exactly once."""
    from types import SimpleNamespace

    from automodel_tpu.data.loader import DataLoader
    from automodel_tpu.data.nanogpt import BlendedNanogptDataset
    from automodel_tpu.data.prefetch import PrefetchConfig, PrefetchingLoader
    from automodel_tpu.recipes.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction as _R,
    )

    a, b = _nanogpt_sources(tmp_path)
    sources = [{"paths": str(a), "weight": 1.0}, {"paths": str(b), "weight": 1.0}]

    def make_loader():
        ds = BlendedNanogptDataset(sources, seq_length=16, seed=9, num_samples=40)
        return PrefetchingLoader(
            DataLoader(ds, global_batch_size=4, shuffle=True, seed=9),
            PrefetchConfig(depth=3, collate_workers=2),
            group_size=1,
        )

    # uninterrupted reference stream (10 batches/epoch, 2 epochs)
    ref_loader = make_loader()
    ref = [item.host for _ in range(2) for item in ref_loader]
    ref_loader.close()
    assert len(ref) == 20
    # the reference stream itself crosses src_a's internal shard boundary
    firsts = {int(h["input_ids"][0, i, 0]) for h in ref for i in range(4)}
    assert any(200 <= t < 400 for t in firsts), "no window from src_a shard 1"
    assert any(t < 200 for t in firsts) and any(t >= 5000 for t in firsts)

    # interrupted run: consume 3 groups (checkpoint cursor = batch 3), then
    # a rollback at fail_step 7 fast-forwards 4 more batches (steps 4..7)
    live = make_loader()
    it = iter(live)
    for _ in range(3):
        next(it)
    r = object.__new__(_R)
    r.dataloader = live
    r.step_scheduler = SimpleNamespace(step=3, epoch=0, grad_acc_steps=1)
    r.checkpointer = SimpleNamespace(has_checkpoint=lambda: True, wait=lambda: None)
    r.telemetry = SimpleNamespace(record_step=lambda rec: None)
    r.resilience = SimpleNamespace(rollbacks=1)
    r._restore = lambda before_step: None
    r._rollback(fail_step=7)
    assert (live.epoch, live.batch_in_epoch) == (0, 7)
    cont = [item.host for item in live]  # rest of epoch 0
    cont += [item.host for item in live]  # epoch 1
    live.close()
    assert len(cont) == len(ref) - 7
    for got, want in zip(cont, ref[7:]):
        for k in got:
            np.testing.assert_array_equal(got[k], want[k])


def test_pretrain_e2e_with_megatron_data(corpus, tmp_path):
    """Recipe-driven pretrain on indexed data (reference: megatron data
    functional tests, tests/functional_tests/training)."""
    from automodel_tpu.config.loader import ConfigNode
    from automodel_tpu.recipes.train_ft import TrainFinetuneRecipeForNextTokenPrediction

    cfg = ConfigNode(
        {
            "seed": 0,
            "model": {
                "hf_config": {
                    "architectures": ["LlamaForCausalLM"],
                    "model_type": "llama",
                    "vocab_size": 1024,
                    "hidden_size": 64,
                    "intermediate_size": 128,
                    "num_hidden_layers": 2,
                    "num_attention_heads": 4,
                    "num_key_value_heads": 2,
                    "head_dim": 16,
                },
                "backend": {"attn": "sdpa", "compute_dtype": "float32", "param_dtype": "float32"},
            },
            "distributed": {"dp_shard": -1},
            "dataset": {
                "_target_": "automodel_tpu.data.megatron.gpt_dataset.MegatronPretraining",
                "paths": str(corpus),
                "seq_length": 32,
                "num_samples": 64,
            },
            "dataloader": {"global_batch_size": 8},
            "step_scheduler": {"max_steps": 3},
            "optimizer": {"name": "adamw", "lr": 1e-3},
            "logging": {"metrics_path": str(tmp_path / "m.jsonl")},
        }
    )
    r = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    r.setup()
    last = r.run_train_validation_loop()
    assert np.isfinite(last["loss"])


def test_build_mapping_structure():
    """build_mapping (reference helpers.cpp:266): rows partition each doc's
    sentences in order (pre-shuffle), targets within [2, max], C++ and
    fallback agree structurally and the C++ path is deterministic."""
    import numpy as np

    from automodel_tpu.data.megatron import helpers as H

    rng = np.random.default_rng(0)
    n_docs = 12
    sent_counts = rng.integers(1, 9, n_docs)
    docs = np.concatenate([[0], np.cumsum(sent_counts)]).astype(np.int64)
    sizes = rng.integers(5, 60, int(docs[-1])).astype(np.int32)
    sizes[3] = 600  # long sentence → its whole doc must be skipped
    kwargs = dict(num_epochs=1, max_num_samples=10_000, max_seq_length=64,
                  short_seq_prob=0.2, seed=7, min_num_sent=2)

    for impl in (H.build_mapping, H._build_mapping_py):
        rows = impl(docs, sizes, **kwargs)
        assert rows.shape[1] == 3 and len(rows) > 0
        assert (rows[:, 0] < rows[:, 1]).all()
        assert (rows[:, 2] >= 2).all() and (rows[:, 2] <= 64).all()
        # no row crosses a document boundary; the long-sentence doc is absent
        long_doc = int(np.searchsorted(docs, 3, side="right") - 1)
        for s0, s1, _ in rows:
            d = int(np.searchsorted(docs, s0, side="right") - 1)
            assert s1 <= docs[d + 1]
            assert d != long_doc
        # per-doc coverage: each qualifying doc's rows tile its sentences
        # contiguously (one epoch: first row starts at docs[d], consecutive
        # rows abut, last ends at docs[d+1])
        for d in range(n_docs):
            dr = rows[(rows[:, 0] >= docs[d]) & (rows[:, 1] <= docs[d + 1])]
            if not len(dr):
                continue
            dr = dr[np.argsort(dr[:, 0])]
            assert dr[0, 0] == docs[d]
            assert dr[-1, 1] == docs[d + 1]
            assert (dr[1:, 0] == dr[:-1, 1]).all()
    if H._load() is not None:
        a = H.build_mapping(docs, sizes, **kwargs)
        b = H.build_mapping(docs, sizes, **kwargs)
        np.testing.assert_array_equal(a, b)


def test_build_blocks_mapping_structure():
    import numpy as np

    from automodel_tpu.data.megatron import helpers as H

    rng = np.random.default_rng(1)
    n_docs = 8
    sent_counts = rng.integers(2, 7, n_docs)
    docs = np.concatenate([[0], np.cumsum(sent_counts)]).astype(np.int64)
    sizes = rng.integers(5, 40, int(docs[-1])).astype(np.int32)
    titles = rng.integers(2, 10, n_docs).astype(np.int32)
    rows = H.build_blocks_mapping(
        docs, sizes, titles, num_epochs=1, max_num_samples=10_000,
        max_seq_length=48, seed=3,
    )
    assert rows.shape[1] == 4 and len(rows) > 0
    assert (rows[:, 0] < rows[:, 1]).all()
    for s0, s1, d, _bid in rows:
        assert docs[d] <= s0 and s1 <= docs[d + 1]
    # block ids unique within the epoch
    assert len(set(rows[:, 3].tolist())) == len(rows)


def test_build_exhaustive_blending_indices_exact_counts():
    import numpy as np

    from automodel_tpu.data.megatron import helpers as H

    sizes = np.asarray([5, 2, 9], np.int64)
    d_idx, s_idx = H.build_exhaustive_blending_indices(sizes)
    assert len(d_idx) == 16
    for d, n in enumerate(sizes):
        sel = d_idx == d
        assert sel.sum() == n
        np.testing.assert_array_equal(np.sort(s_idx[sel]), np.arange(n))
