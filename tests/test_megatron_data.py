"""Megatron-style data pipeline: indexed datasets, native index builders,
GPT dataset, blending, nanoGPT shards, and an e2e pretrain step."""

import numpy as np
import pytest

from automodel_tpu.data.megatron.gpt_dataset import (
    BlendedDataset,
    GPTDataset,
    MegatronPretraining,
)
from automodel_tpu.data.megatron.helpers import (
    _build_sample_idx_py,
    _load,
    build_blending_indices,
    build_sample_idx,
)
from automodel_tpu.data.megatron.indexed_dataset import (
    IndexedDataset,
    IndexedDatasetWriter,
)


@pytest.fixture()
def corpus(tmp_path):
    rng = np.random.default_rng(0)
    with IndexedDatasetWriter(tmp_path / "corpus", dtype=np.uint16) as w:
        for _ in range(50):
            w.add_document(rng.integers(0, 1000, size=rng.integers(5, 120)))
    return tmp_path / "corpus"


def test_indexed_roundtrip(corpus):
    ds = IndexedDataset(corpus)
    assert len(ds) == 50
    assert ds.dtype == np.uint16
    assert ds.num_tokens == int(ds.sizes.sum())
    d0 = ds[0]
    assert len(d0) == ds.sizes[0]
    np.testing.assert_array_equal(ds.get_slice(3, 2, 3), ds[3][2:5])


def test_native_helpers_compiled_and_match_python():
    assert _load() is not None, "C++ helpers failed to compile"
    rng = np.random.default_rng(1)
    sizes = rng.integers(3, 50, size=40).astype(np.int32)
    doc_idx = np.tile(np.arange(40, dtype=np.int64), 4)
    rng.shuffle(doc_idx)
    native = build_sample_idx(sizes, doc_idx, 64, 20)
    py = _build_sample_idx_py(sizes, doc_idx, 64, 20)
    np.testing.assert_array_equal(native, py)


def test_sample_idx_exhaustion_raises():
    sizes = np.asarray([10], np.int32)
    with pytest.raises(ValueError, match="exhaust"):
        build_sample_idx(sizes, np.zeros(1, np.int64), 64, 5)


def test_blending_proportions():
    d_idx, s_idx = build_blending_indices(np.asarray([0.7, 0.2, 0.1]), 1000)
    counts = np.bincount(d_idx, minlength=3)
    np.testing.assert_allclose(counts / 1000, [0.7, 0.2, 0.1], atol=0.01)
    # per-dataset sample indices are sequential
    for d in range(3):
        np.testing.assert_array_equal(
            s_idx[d_idx == d], np.arange(counts[d])
        )


def test_gpt_dataset_samples(corpus):
    ds = GPTDataset(str(corpus), seq_length=32, num_samples=40, seed=0)
    assert len(ds) == 40
    ex = ds[0]
    assert ex["input_ids"].shape == (32,) and ex["labels"].shape == (32,)
    # next-token alignment inside the window
    np.testing.assert_array_equal(ex["input_ids"][1:], ex["labels"][:-1])
    # determinism
    ds2 = GPTDataset(str(corpus), seq_length=32, num_samples=40, seed=0)
    np.testing.assert_array_equal(ds[7]["input_ids"], ds2[7]["input_ids"])


def test_blended_and_wrapper(corpus, tmp_path):
    rng = np.random.default_rng(2)
    with IndexedDatasetWriter(tmp_path / "c2", dtype=np.uint16) as w:
        for _ in range(20):
            w.add_document(rng.integers(0, 1000, size=60))
    mp = MegatronPretraining(
        [str(corpus), str(tmp_path / "c2")], seq_length=16,
        num_samples=30, weights=[0.5, 0.5],
    )
    assert len(mp) == 30
    assert mp[0]["input_ids"].shape == (16,)


def test_nanogpt_dataset(tmp_path):
    from automodel_tpu.data.nanogpt import NanogptDataset

    tokens = np.arange(1000, dtype=np.uint16)
    (tmp_path / "shard0.bin").write_bytes(tokens.tobytes())
    ds = NanogptDataset(tmp_path, seq_length=64)
    assert len(ds) > 0
    ex = ds[1]
    np.testing.assert_array_equal(ex["input_ids"][1:], ex["labels"][:-1])
    assert ex["input_ids"][0] == 64  # stride = seq_length


def test_pretrain_e2e_with_megatron_data(corpus, tmp_path):
    """Recipe-driven pretrain on indexed data (reference: megatron data
    functional tests, tests/functional_tests/training)."""
    from automodel_tpu.config.loader import ConfigNode
    from automodel_tpu.recipes.train_ft import TrainFinetuneRecipeForNextTokenPrediction

    cfg = ConfigNode(
        {
            "seed": 0,
            "model": {
                "hf_config": {
                    "architectures": ["LlamaForCausalLM"],
                    "model_type": "llama",
                    "vocab_size": 1024,
                    "hidden_size": 64,
                    "intermediate_size": 128,
                    "num_hidden_layers": 2,
                    "num_attention_heads": 4,
                    "num_key_value_heads": 2,
                    "head_dim": 16,
                },
                "backend": {"attn": "sdpa", "compute_dtype": "float32", "param_dtype": "float32"},
            },
            "distributed": {"dp_shard": 1},
            "dataset": {
                "_target_": "automodel_tpu.data.megatron.gpt_dataset.MegatronPretraining",
                "paths": str(corpus),
                "seq_length": 32,
                "num_samples": 64,
            },
            "dataloader": {"global_batch_size": 8},
            "step_scheduler": {"max_steps": 3},
            "optimizer": {"name": "adamw", "lr": 1e-3},
            "logging": {"metrics_path": str(tmp_path / "m.jsonl")},
        }
    )
    r = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    r.setup()
    last = r.run_train_validation_loop()
    assert np.isfinite(last["loss"])
