"""Pallas ring-flash kernels (ops/ring_flash.py) vs global sdpa.

AUTOMODEL_RING_INTERPRET=1 runs the REAL kernel code through the pallas
interpreter on the CPU mesh — same scheme as the splash/gmm tests. Parity
target: the reference's fused-attention-inside-CP-ring
(components/moe/parallelizer.py:279-297, cp_comm_type="p2p").
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from automodel_tpu.utils.compat import shard_map
from automodel_tpu.ops.attention import sdpa
from automodel_tpu.parallel import cp as cpm


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("AUTOMODEL_RING_INTERPRET", "1")


def _run_ring(mesh, q, k, v, seg, *, window, zigzag):
    inner = functools.partial(
        cpm.ring_attention_shard, axis_name="cp", causal=True,
        sliding_window=window, zigzag=zigzag, platform="cpu",
    )
    spec = P(None, "cp", None, None)
    if seg is not None:
        mapped = shard_map(
            lambda a, b, c, s: inner(a, b, c, segment_ids=s),
            mesh=mesh, in_specs=(spec, spec, spec, P(None, "cp")),
            out_specs=spec, check_vma=False,
        )
        return mapped, (q, k, v, seg)
    mapped = shard_map(
        lambda a, b, c: inner(a, b, c),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )
    return mapped, (q, k, v)


@pytest.mark.parametrize("zigzag", [False, True])
@pytest.mark.parametrize("window", [None, 96])
@pytest.mark.parametrize("use_seg", [False, True])
def test_ring_flash_parity(devices8, zigzag, window, use_seg):
    cp = 4
    mesh = Mesh(np.array(devices8[:cp]), ("cp",))
    rng = np.random.default_rng(0)
    B, S, N, NKV, H = 2, 256, 4, 2, 32
    q = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, NKV, H)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, NKV, H)), jnp.float32)
    seg = None
    if use_seg:
        half = jnp.asarray(
            rng.integers(0, 3, size=(B, 1)).repeat(S // 2, 1), jnp.int32
        )
        seg = jnp.concatenate([half, half + 1], axis=1)

    ref = sdpa(q, k, v, causal=True, segment_ids=seg, sliding_window=window)
    dref = jax.grad(
        lambda q, k, v: (
            sdpa(q, k, v, causal=True, segment_ids=seg, sliding_window=window) ** 2
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)

    qq, kk, vv, ss = q, k, v, seg
    if zigzag:
        qq = cpm.apply_zigzag(q, cp, axis=1)
        kk = cpm.apply_zigzag(k, cp, axis=1)
        vv = cpm.apply_zigzag(v, cp, axis=1)
        ss = cpm.apply_zigzag(seg, cp, axis=1) if use_seg else None
    mapped, args = _run_ring(mesh, qq, kk, vv, ss, window=window, zigzag=zigzag)
    out = jax.jit(mapped)(*args)
    grads = jax.jit(
        jax.grad(lambda *a: (mapped(*a) ** 2).sum(), argnums=(0, 1, 2))
    )(*args)
    if zigzag:
        out = cpm.undo_zigzag(out, cp, axis=1)
        grads = tuple(cpm.undo_zigzag(g, cp, axis=1) for g in grads)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
    for g, r in zip(grads, dref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-3)


def test_ring_flash_fully_masked_rows(devices8):
    """First tokens of a fresh segment boundary on a far rank must come out
    zero, not NaN (all-masked guard in the kernel + merge)."""
    cp = 2
    mesh = Mesh(np.array(devices8[:cp]), ("cp",))
    rng = np.random.default_rng(1)
    B, S, N, H = 1, 128, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, N, H)), jnp.float32)
    # every token its own segment → each token only attends to itself
    seg = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, 0)
    mapped, args = _run_ring(mesh, q, k, v, seg, window=None, zigzag=False)
    out = jax.jit(mapped)(*args)
    assert bool(jnp.isfinite(out).all())
    ref = sdpa(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
