"""DiT diffusion: patchify round-trip, adaLN-Zero identity init, DDPM loss
decreases, pipeline per-component sharded placement, LoRA dropout
integration. Reference parity target: _diffusers/auto_diffusion_pipeline.py
+ the Wan DiT strategy (parallelizer.py:281)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.diffusion import (
    AutoDiffusionPipeline,
    DiTConfig,
    DiTModel,
    make_diffusion_loss,
)
from automodel_tpu.models.common.config import BackendConfig

FP32 = BackendConfig(param_dtype="float32", compute_dtype="float32")


def _tiny():
    cfg = DiTConfig(image_size=16, patch_size=4, in_channels=3,
                    hidden_size=64, num_layers=2, num_heads=2, num_classes=5)
    return cfg, DiTModel(cfg, FP32)


def test_patchify_round_trip():
    cfg, model = _tiny()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)), jnp.float32)
    p = model.patchify(x)
    assert p.shape == (2, cfg.num_patches, cfg.patch_dim)
    # unpatchify inverts patchify when out_channels == in_channels
    back = model.unpatchify(p.reshape(2, cfg.num_patches, -1))
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-6)


def test_adaln_zero_identity_at_init():
    """adaLN-Zero: zero-gated blocks + zero output head → the initial model
    output is exactly zero regardless of input (the DiT identity-start)."""
    cfg, model = _tiny()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)), jnp.float32)
    out = model(params, x, jnp.asarray([0, 500]), jnp.asarray([1, 2]))
    assert out.shape == (2, 16, 16, 3)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_ddpm_training_loss_decreases():
    from automodel_tpu.optim.builders import build_optimizer
    from automodel_tpu.training.train_state import TrainState
    from automodel_tpu.training.train_step import build_train_step

    cfg, model = _tiny()
    params = model.init(jax.random.PRNGKey(0))
    loss_fn = make_diffusion_loss(model, num_train_timesteps=100)
    opt = build_optimizer(name="adamw", lr=3e-3)
    state = TrainState.create(params, jax.jit(opt.init)(params))
    step = build_train_step(loss_fn, opt)
    rng = np.random.default_rng(0)
    # fixed clean latents; fresh noise each step via step_seed
    x = np.asarray(rng.normal(size=(1, 8, 16, 16, 3)), np.float32)
    losses = []
    for i in range(12):
        b = {"x": x, "y": np.asarray(rng.integers(0, 5, (1, 8)), np.int32),
             "step_seed": np.asarray([[i]], np.int32)}
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses


def test_pipeline_from_model_index(tmp_path):
    """Generic Diffusers-pipeline ingestion (reference
    auto_diffusion_pipeline.py:79-140) WITHOUT the diffusers package: the
    on-disk layout is JSON + safetensors. Module components load via the
    converter registry; schedulers ride along as passive configs; a module
    component with no converter is a loud error naming its class."""
    import json

    from automodel_tpu.checkpoint.hf_io import _write_safetensors

    cfg, model = _tiny()
    params = model.init(jax.random.PRNGKey(0))
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in p): np.asarray(v)
        for p, v in jax.tree_util.tree_leaves_with_path(params)
    }
    tdir = tmp_path / "transformer"
    tdir.mkdir()
    _write_safetensors(tdir / "model.safetensors", flat)
    (tdir / "config.json").write_text(json.dumps({
        "_class_name": "DiTModel", "image_size": 16, "patch_size": 4,
        "in_channels": 3, "hidden_size": 64, "num_layers": 2,
        "num_heads": 2, "num_classes": 5,
    }))
    sdir = tmp_path / "scheduler"
    sdir.mkdir()
    (sdir / "scheduler_config.json").write_text(
        json.dumps({"_class_name": "DDPMScheduler", "num_train_timesteps": 100})
    )
    (tmp_path / "model_index.json").write_text(json.dumps({
        "_class_name": "DiTPipeline", "_diffusers_version": "0.31.0",
        "transformer": ["diffusers", "DiTModel"],
        "scheduler": ["diffusers", "DDPMScheduler"],
    }))

    pipe = AutoDiffusionPipeline.from_pretrained(str(tmp_path))
    m, p = pipe["transformer"]
    assert m.config.hidden_size == 64
    assert pipe.configs["scheduler"]["num_train_timesteps"] == 100
    # loaded params run and match the originals
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 16, 16, 3)), jnp.float32)
    t = jnp.asarray([3, 7], jnp.int32)
    y = jnp.asarray([1, 2], jnp.int32)
    np.testing.assert_allclose(
        np.asarray(m(p, x, t, y)), np.asarray(model(params, x, t, y)), atol=1e-6
    )

    # an unconvertible torch module component fails loudly by class name
    vdir = tmp_path / "vae"
    vdir.mkdir()
    _write_safetensors(vdir / "model.safetensors", {"w": np.ones((2, 2), np.float32)})
    (vdir / "config.json").write_text(json.dumps({"_class_name": "AutoencoderKL"}))
    (tmp_path / "model_index.json").write_text(json.dumps({
        "_class_name": "DiTPipeline",
        "transformer": ["diffusers", "DiTModel"],
        "vae": ["diffusers", "AutoencoderKL"],
    }))
    with pytest.raises(NotImplementedError, match="AutoencoderKL"):
        AutoDiffusionPipeline.from_pretrained(str(tmp_path))


def test_pipeline_sharded_placement(devices8):
    from automodel_tpu.parallel.mesh import MeshConfig, build_mesh

    ctx = build_mesh(MeshConfig(dp_shard=4, tp=2), devices=devices8)
    cfg, model = _tiny()
    params = model.init(jax.random.PRNGKey(0))
    pipe = AutoDiffusionPipeline.from_components(
        {"transformer": (model, params),
         "vae": (None, {"w": jnp.ones((8, 8))})},  # unmapped → replicated
        ctx,
    )
    _, tp = pipe["transformer"]
    spec = tp["blocks"]["qkv"]["kernel"].sharding.spec
    assert "tensor" not in str(spec)  # logical axes resolved to mesh axes
    assert str(spec) != "PartitionSpec()"
    _, vp = pipe["vae"]
    assert str(vp["w"].sharding.spec) == "PartitionSpec()"
