"""Optimizer numerics contracts.

The single-microbatch train-step fast path feeds BF16 grads straight into
the optimizer (training/train_step.py). optax's scale_by_adam inherits the
update dtype for its moments — bf16 nu's half-ulp exceeds the (1-b2)·g²
increment at b2=0.999 and the second moment freezes. These tests pin the
repo's adam to fp32 moments and the clip to fp32 norm accumulation
regardless of grad dtype (torch AdamW parity: fp32 exp_avg/exp_avg_sq).
"""

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.optim.builders import (
    build_optimizer,
    clip_by_global_norm_fp32,
    scale_by_adam_fp32_moments,
)


def test_adam_moments_stay_fp32_with_bf16_grads():
    params = {"w": jnp.ones((8, 8), jnp.bfloat16)}
    opt = scale_by_adam_fp32_moments(b1=0.9, b2=0.999)
    state = opt.init(params)
    assert state.mu["w"].dtype == jnp.float32
    assert state.nu["w"].dtype == jnp.float32
    g = {"w": jnp.full((8, 8), 1e-2, jnp.bfloat16)}
    nu_prev = None
    for _ in range(5):
        upd, state = opt.update(g, state)
        assert state.nu["w"].dtype == jnp.float32
        nu = float(state.nu["w"][0, 0])
        if nu_prev is not None:
            # the second moment must keep ACCUMULATING: with bf16 moments the
            # (1-b2)*g^2 increment rounds to a no-op after the first step
            assert nu > nu_prev, (nu, nu_prev)
        nu_prev = nu


def test_adam_fp32_moments_matches_optax_on_fp32_grads():
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    g = {"w": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    ours = scale_by_adam_fp32_moments(b1=0.9, b2=0.95, eps=1e-8)
    ref = __import__("optax").scale_by_adam(b1=0.9, b2=0.95, eps=1e-8)
    so, sr = ours.init(params), ref.init(params)
    for _ in range(3):
        uo, so = ours.update(g, so)
        ur, sr = ref.update(g, sr)
        np.testing.assert_allclose(uo["w"], ur["w"], rtol=1e-6)


def test_clip_fp32_does_not_saturate_on_bf16():
    # 1M bf16 elements of equal magnitude: bf16 partial sums saturate, the
    # fp32 clip must still compute the true norm (=10.0) and scale correctly
    g = {"w": jnp.full((1024, 1024), 10.0 / 1024.0, jnp.bfloat16)}
    clip = clip_by_global_norm_fp32(1.0)
    upd, _ = clip.update(g, clip.init(g))
    norm_after = float(
        jnp.sqrt(jnp.sum(jnp.square(upd["w"].astype(jnp.float32))))
    )
    np.testing.assert_allclose(norm_after, 1.0, rtol=2e-2)


def test_build_optimizer_end_to_end_bf16_loss_decreases():
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(64, 16))
    w_true = rng.normal(size=(16,))
    x = jnp.asarray(xs, jnp.bfloat16)
    y = jnp.asarray(xs @ w_true, jnp.bfloat16)  # fittable target
    params = {"w": jnp.zeros((16,), jnp.bfloat16)}
    opt = build_optimizer(name="adamw", lr=1e-2, weight_decay=0.01,
                          grad_clip_norm=1.0)
    state = opt.init(params)

    def loss_fn(p):
        pred = x @ p["w"]
        return jnp.mean(jnp.square(pred - y).astype(jnp.float32))

    losses = []
    for _ in range(50):
        l, g = jax.value_and_grad(loss_fn)(params)
        upd, state = opt.update(g, state, params)
        params = jax.tree.map(
            lambda p, u: (p.astype(jnp.float32) + u.astype(jnp.float32)).astype(p.dtype),
            params, upd,
        )
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
