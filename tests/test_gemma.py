"""Gemma 2 / Gemma 3: HF numerical parity (soft caps, sandwich norms,
zero-centered RMSNorm, alternating local/global attention, dual rope)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.common.config import BackendConfig
from automodel_tpu.models.gemma import (
    GemmaConfig,
    GemmaForCausalLM,
    GemmaStateDictAdapter,
)

FP32 = BackendConfig(attn="sdpa", param_dtype="float32", compute_dtype="float32")


def _hf_tiny(which: str):
    import torch

    torch.manual_seed(0)
    if which == "gemma2":
        from transformers import Gemma2Config, Gemma2ForCausalLM

        cfg = Gemma2Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, max_position_embeddings=256, sliding_window=8,
            query_pre_attn_scalar=16, attn_logit_softcapping=50.0,
            final_logit_softcapping=30.0, attn_implementation="eager",
        )
        return cfg, Gemma2ForCausalLM(cfg).eval()
    from transformers import Gemma3TextConfig, Gemma3ForCausalLM

    cfg = Gemma3TextConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=7, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256, sliding_window=8,
        query_pre_attn_scalar=16, rope_theta=1_000_000.0,
        rope_local_base_freq=10_000.0, attn_implementation="eager",
    )
    return cfg, Gemma3ForCausalLM(cfg).eval()


@pytest.mark.parametrize("which", ["gemma2", "gemma3"])
def test_logits_parity_with_hf(which):
    import torch

    hf_cfg, hf_model = _hf_tiny(which)
    cfg = GemmaConfig.from_hf(hf_cfg)
    assert cfg.embed_scale == 8.0  # sqrt(64)
    if which == "gemma2":
        assert cfg.attn_soft_cap == 50.0 and cfg.logits_soft_cap == 30.0
        assert cfg.layer_types[0] == "sliding_attention"
        assert cfg.layer_types[1] == "full_attention"
    else:
        assert cfg.qk_norm
        assert cfg.layer_types[5] == "full_attention"  # 5 local : 1 global
    model = GemmaForCausalLM(cfg, FP32)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    params = jax.tree.map(jnp.asarray, GemmaStateDictAdapter(cfg).from_hf(lambda k: sd[k]))
    ids = np.random.default_rng(0).integers(0, 128, size=(2, 32))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    out = np.asarray(model(params, jnp.asarray(ids)))
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=3e-3)


def test_scan_matches_unrolled():
    hf_cfg, hf_model = _hf_tiny("gemma3")
    cfg = GemmaConfig.from_hf(hf_cfg)
    m_scan = GemmaForCausalLM(cfg, FP32)
    import dataclasses as dc

    m_loop = GemmaForCausalLM(
        cfg, dc.replace(FP32, scan_layers=False)
    )
    params = m_scan.init(jax.random.key(0))
    ids = jnp.arange(24).reshape(1, 24) % 128
    np.testing.assert_allclose(
        np.asarray(m_scan(params, ids)),
        np.asarray(m_loop(params, ids)),
        atol=1e-5,
        rtol=1e-5,
    )


def test_registry_dispatch():
    from automodel_tpu import auto_model

    hf = {
        "architectures": ["Gemma2ForCausalLM"],
        "model_type": "gemma2",
        "vocab_size": 128,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 16,
        "query_pre_attn_scalar": 16,
        "sliding_window": 8,
    }
    auto = auto_model.from_config(
        hf, None, {"attn": "sdpa", "compute_dtype": "float32", "param_dtype": "float32"}
    )
    out = auto.model(auto.params, jnp.arange(16).reshape(1, 16) % 128)
    assert out.shape == (1, 16, 128)
