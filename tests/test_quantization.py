"""QAT (STE fake-quant, delayed enablement) + QLoRA (NF4 base).

Reference parity targets: quantization/qat.py:46,125-146 (torchao fake-quant
quantizers with enable/disable hooks) and qlora.py:22 (bitsandbytes NF4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu import auto_model
from automodel_tpu.quantization import (
    QATConfig,
    QLoRAConfig,
    fake_quant_weight,
    make_qat_loss_fn,
    nf4_dequantize,
    nf4_dequantize_tree,
    nf4_quantize,
    nf4_quantize_tree,
)

HF = {
    "architectures": ["LlamaForCausalLM"],
    "model_type": "llama",
    "vocab_size": 128,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 16,
}
FP32 = {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32"}


# ---- QAT -------------------------------------------------------------------
def test_fake_quant_levels_and_ste():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    q = fake_quant_weight(w, groupsize=32)
    # per group of 32 input rows, at most 16 distinct levels per output col
    qn = np.asarray(q)
    for col in range(4):
        grp = qn[:32, col]
        assert len(np.unique(np.round(grp / (np.abs(grp).max() / 7 + 1e-12)))) <= 16
    # straight-through: gradient of sum(fq(w)) is exactly ones
    g = jax.grad(lambda w: fake_quant_weight(w, 32).sum())(w)
    np.testing.assert_array_equal(np.asarray(g), np.ones_like(np.asarray(g)))
    # quantization changes values (it's not a no-op)
    assert float(jnp.abs(q - w).max()) > 0


def test_qat_delayed_enablement_and_training():
    from automodel_tpu.optim.builders import build_optimizer
    from automodel_tpu.training.train_state import TrainState
    from automodel_tpu.training.train_step import build_train_step, make_causal_lm_loss

    auto = auto_model.from_config(HF, None, FP32, seed=0)
    base_loss = make_causal_lm_loss(auto.model)
    qat_loss = make_qat_loss_fn(base_loss, QATConfig(
        quantizer_type="int4_weight_only", groupsize=32, start_step=2,
    ))
    assert qat_loss.needs_step

    ids = np.random.default_rng(1).integers(0, 128, size=(1, 12)).astype(np.int32)
    mb = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
    # before start_step the transform is a no-op; after it, losses differ
    l_pre, _ = qat_loss(auto.params, mb, step=jnp.asarray(0))
    l_base, _ = base_loss(auto.params, mb)
    l_post, _ = qat_loss(auto.params, mb, step=jnp.asarray(5))
    np.testing.assert_allclose(float(l_pre), float(l_base), rtol=1e-6)
    assert abs(float(l_post) - float(l_base)) > 1e-6

    # end-to-end: train step consumes the step-threaded loss and learns
    opt = build_optimizer(name="adamw", lr=5e-3)
    state = TrainState.create(auto.params, jax.jit(opt.init)(auto.params))
    step = build_train_step(qat_loss, opt)
    batch = {"input_ids": jnp.asarray(ids)[None], "labels": jnp.asarray(ids)[None]}
    losses = []
    for _ in range(6):
        state, metrics = step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert losses[-1] < losses[0]


def test_qat_config_validates():
    with pytest.raises(ValueError):
        QATConfig(quantizer_type="fp3")


# ---- QLoRA -----------------------------------------------------------------
def test_nf4_round_trip_error_bounded():
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    q = nf4_quantize(w, blocksize=64)
    assert q["codes"].dtype == jnp.uint8
    assert q["codes"].size == w.size // 2  # 4 bits/param packed
    back = nf4_dequantize(q)
    assert back.shape == w.shape and back.dtype == w.dtype
    err = float(jnp.abs(back - w).max() / jnp.abs(w).max())
    assert err < 0.2  # nf4 with absmax block scaling
    # deterministic round trip through quantize again
    q2 = nf4_quantize(back, blocksize=64)
    np.testing.assert_array_equal(np.asarray(q2["codes"]), np.asarray(q["codes"]))


def test_qlora_tree_and_training():
    from automodel_tpu.peft import PeftConfig, init_lora_params, make_lora_loss_fn
    from automodel_tpu.optim.builders import build_optimizer
    from automodel_tpu.training.train_state import TrainState
    from automodel_tpu.training.train_step import build_train_step, make_causal_lm_loss

    auto = auto_model.from_config(HF, None, FP32, seed=0)
    qcfg = QLoRAConfig(min_size=1024, blocksize=64)
    qtree = nf4_quantize_tree(auto.params, qcfg)
    # big kernels are packed, embeddings/norms untouched
    assert "codes" in qtree["layers"]["attn"]["q_proj"]["kernel"]
    assert not isinstance(qtree["embed"]["embedding"], dict) or "codes" not in qtree[
        "embed"
    ]["embedding"]

    pcfg = PeftConfig(target_modules=("*attn/[qkvo]_proj*", "*mlp*"), dim=4, alpha=8)
    lora = init_lora_params(jax.random.key(0), auto.params, pcfg)
    base_loss = make_causal_lm_loss(auto.model)
    loss_fn = make_lora_loss_fn(
        base_loss, qtree, pcfg,
        graft_patterns=auto.model.lora_graft_patterns,
        base_transform=nf4_dequantize_tree,
    )
    ids = np.random.default_rng(3).integers(0, 128, size=(1, 2, 12)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}

    # loss at init is close to the full-precision base (nf4 error only) —
    # checked BEFORE training: the train step donates the lora buffers
    fp_loss = make_lora_loss_fn(
        base_loss, auto.params, pcfg,
        graft_patterns=auto.model.lora_graft_patterns,
    )
    mb = {k: v[0] for k, v in batch.items()}
    l_q = float(loss_fn(lora, mb, qtree)[0])
    l_f = float(fp_loss(lora, mb, auto.params)[0])
    assert abs(l_q - l_f) / abs(l_f) < 0.1

    opt = build_optimizer(name="adamw", lr=1e-2)
    state = TrainState.create(lora, jax.jit(opt.init)(lora))
    step = build_train_step(loss_fn, opt)
    losses = []
    for _ in range(6):
        state, metrics = step(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert losses[-1] < losses[0]
