"""Qwen3-Omni-MoE thinker: HF numerical parity of the text stack under
interleaved M-RoPE (1-D and 3-D positions), adapter round-trip with the
thinker prefix, registry train smoke. Reference parity target:
components/models/qwen3_omni_moe."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.common.config import BackendConfig
from automodel_tpu.models.qwen3_omni_moe import (
    Qwen3OmniMoeStateDictAdapter,
    Qwen3OmniMoeThinkerConfig,
    Qwen3OmniMoeThinkerForCausalLM,
)

FP32 = BackendConfig(
    attn="sdpa", param_dtype="float32", compute_dtype="float32",
    experts="dense", scan_layers=False,
)


def _hf_tiny():
    import torch

    torch.manual_seed(0)
    from transformers.models.qwen3_omni_moe.configuration_qwen3_omni_moe import (
        Qwen3OmniMoeTextConfig,
    )
    from transformers.models.qwen3_omni_moe.modeling_qwen3_omni_moe import (
        Qwen3OmniMoeThinkerTextModel,
    )

    cfg = Qwen3OmniMoeTextConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        moe_intermediate_size=16,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=8,
        num_experts=4,
        num_experts_per_tok=2,
        decoder_sparse_step=1,
        norm_topk_prob=True,
        max_position_embeddings=256,
        rope_theta=10_000.0,
        rope_scaling={"rope_type": "default", "mrope_section": [2, 1, 1]},
        tie_word_embeddings=True,
        attn_implementation="eager",
    )
    return cfg, Qwen3OmniMoeThinkerTextModel(cfg).eval()


@pytest.fixture(scope="module")
def parity_setup():
    hf_cfg, hf_model = _hf_tiny()
    cfg = Qwen3OmniMoeThinkerConfig.from_hf(hf_cfg.to_dict())
    model = Qwen3OmniMoeThinkerForCausalLM(cfg, FP32)
    adapter = Qwen3OmniMoeStateDictAdapter(cfg)
    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}

    def get_tensor(k):  # thinker.model.X → the bare text-model key X
        assert k.startswith("thinker.model."), k
        return sd[k[len("thinker.model."):]]

    from automodel_tpu.checkpoint.hf_io import assemble_tree

    params = assemble_tree(adapter.iter_from_hf(get_tensor))
    params = jax.tree.map(jnp.asarray, params)
    return hf_cfg, hf_model, cfg, model, params


def test_hidden_parity_1d_positions(parity_setup):
    import torch

    _, hf_model, _, model, params = parity_setup
    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        ref = hf_model(input_ids=torch.tensor(ids)).last_hidden_state.numpy()
    got, _ = model.hidden(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-4, rtol=2e-3)


def test_hidden_parity_3d_positions(parity_setup):
    import torch

    _, hf_model, _, model, params = parity_setup
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 128, (2, 10))
    pos = rng.integers(0, 50, (3, 2, 10))  # distinct t/h/w streams
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.tensor(ids), position_ids=torch.tensor(pos)
        ).last_hidden_state.numpy()
    got, _ = model.hidden(
        params, jnp.asarray(ids), position_ids=jnp.asarray(pos)
    )
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-4, rtol=2e-3)


def test_adapter_round_trip(parity_setup):
    _, _, cfg, _, params = parity_setup
    adapter = Qwen3OmniMoeStateDictAdapter(cfg)
    host = jax.tree.map(np.asarray, params)
    out = dict(adapter.to_hf(host))
    assert all(k.startswith("thinker.") for k in out)
    back_tree_pairs = list(adapter.iter_from_hf(lambda k: out[k]))
    from automodel_tpu.checkpoint.hf_io import assemble_tree

    back = assemble_tree(iter(back_tree_pairs))
    for p, v in jax.tree_util.tree_leaves_with_path(host):
        got = back
        for kk in p:
            got = got[kk.key]
        np.testing.assert_allclose(got, v, atol=1e-6, err_msg=str(p))


def test_registry_train_smoke():
    from automodel_tpu.models.registry import resolve_architecture

    hf = {
        "architectures": ["Qwen3OmniMoeForConditionalGeneration"],
        "thinker_config": {
            "text_config": {
                "model_type": "qwen3_omni_moe_text",
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "moe_intermediate_size": 16, "num_hidden_layers": 2,
                "num_attention_heads": 4, "num_key_value_heads": 2,
                "head_dim": 8, "num_experts": 4, "num_experts_per_tok": 2,
                "norm_topk_prob": True,
                "rope_scaling": {"mrope_section": [2, 1, 1]},
            }
        },
    }
    model, adapter = resolve_architecture(hf)(hf, FP32)
    assert isinstance(model, Qwen3OmniMoeThinkerForCausalLM)
    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 128, (1, 12)))

    def loss(p):
        logits, aux = model(p, ids)
        return jnp.mean(logits.astype(jnp.float32) ** 2) + aux.aux_loss

    g = jax.grad(loss)(params)
    gn = jax.tree_util.tree_reduce(
        lambda a, x: a + jnp.sum(jnp.abs(x.astype(jnp.float32))), g, 0.0
    )
    assert bool(jnp.isfinite(gn)) and float(gn) > 0
