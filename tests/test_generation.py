"""Generation subsystem: KV-cache prefill/decode parity, sampling, engine,
CLI, benchmark-leg degradation, report schema. All CPU-fast, tier-1.

Parity is the ground truth: prefill + token-at-a-time cached decode must
reproduce the FULL no-cache forward — logits within fp32 tolerance at every
decode step, greedy tokens exactly — for the dense llama family, gpt2
(learned positions, no rope), qwen3_moe (the MoE decode path, including a
dense-prefix layer), and the sliding-window ring cache past wraparound.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.generation import kv_cache
from automodel_tpu.generation.engine import (
    GenerationConfig,
    GenerationEngine,
    GenerationUnsupported,
)
from automodel_tpu.generation.loop import build_decode_fn, build_prefill_fn
from automodel_tpu.generation.sampling import SamplingConfig, sample
from automodel_tpu.models.common.config import BackendConfig, TransformerConfig

FP32 = BackendConfig(attn="sdpa", param_dtype="float32", compute_dtype="float32")
GREEDY = SamplingConfig(temperature=0.0)


def test_generation_suite_runs_on_cpu():
    """Tier-1 contract: this whole module must run CPU-only (the conftest
    pins jax_platforms=cpu; nothing here may escape to an accelerator)."""
    assert jax.default_backend() == "cpu"
    assert all(d.platform == "cpu" for d in jax.devices())


# -- model zoo ----------------------------------------------------------------


def _tiny_llama(**over):
    kw = dict(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=3,
        num_heads=4, num_kv_heads=2, head_dim=8,
    )
    kw.update(over)
    cfg = TransformerConfig(**kw)
    from automodel_tpu.models.llama import LlamaForCausalLM

    model = LlamaForCausalLM(cfg, FP32)
    return model, model.init(jax.random.key(0))


def _tiny_gpt2():
    from automodel_tpu.models.gpt2.model import GPT2Config, GPT2ForCausalLM

    cfg = GPT2Config(vocab_size=96, n_positions=64, hidden_size=32, num_layers=2, num_heads=4)
    model = GPT2ForCausalLM(cfg, FP32)
    return model, model.init(jax.random.key(1))


def _tiny_moe():
    from automodel_tpu.models.qwen3_moe import MoEForCausalLM, MoETransformerConfig

    hf = {
        "architectures": ["Qwen3MoeForCausalLM"], "model_type": "qwen3_moe",
        "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
        "moe_intermediate_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "num_experts": 8, "num_experts_per_tok": 2,
        "max_position_embeddings": 256, "tie_word_embeddings": False,
        # one dense-prefix layer: the cache must split across both stacks
        "first_k_dense_replace": 1,
    }
    cfg = MoETransformerConfig.from_hf(hf)
    model = MoEForCausalLM(
        cfg,
        BackendConfig(
            attn="sdpa", experts="dense",
            param_dtype="float32", compute_dtype="float32",
        ),
    )
    return model, model.init(jax.random.key(2))


def _full_logits(model, params, seq):
    out = model(params, jnp.asarray([seq]))
    logits = out[0] if isinstance(out, tuple) else out
    return np.asarray(logits[0], np.float32)


def _cached_stepwise_logits(model, params, prompt, n_steps, capacity=None, window=None):
    """Drive the cache primitives directly: prefill the prompt, then greedy
    decode n_steps, capturing each step's logits. → (step_logits, tokens)."""
    mcfg = model.config
    S = len(prompt)
    capacity = capacity or (S + n_steps)
    cache = kv_cache.init_cache(
        mcfg.num_layers, 1, capacity, mcfg.num_kv_heads, mcfg.head_dim,
        dtype=jnp.float32, window=window,
    )
    lengths = jnp.asarray([S], jnp.int32)
    prefill = build_prefill_fn(lambda p, i, **kw: model(p, i, **kw))
    last, cache = prefill(params, jnp.asarray([prompt], jnp.int32), lengths, cache)
    step_logits = [np.asarray(last[0], np.float32)]
    tok = int(jnp.argmax(last[0]))
    tokens = [tok]
    for _ in range(n_steps - 1):
        kvc, ctx = kv_cache.decode_ctx(cache)
        out = model(
            params, jnp.asarray([[tok]], jnp.int32),
            position_ids=ctx.q_pos[:, None], cache=(kvc, ctx),
        )
        primary, cache = out
        logits = primary[0] if isinstance(primary, tuple) else primary
        step_logits.append(np.asarray(logits[0, -1], np.float32))
        tok = int(jnp.argmax(logits[0, -1]))
        tokens.append(tok)
    return step_logits, tokens


def _assert_stepwise_parity(model, params, prompt, n_steps, window=None, capacity=None, atol=2e-4):
    got_logits, got_tokens = _cached_stepwise_logits(
        model, params, prompt, n_steps, capacity=capacity, window=window
    )
    seq = list(prompt)
    for i in range(n_steps):
        ref = _full_logits(model, params, seq)[-1]
        np.testing.assert_allclose(got_logits[i], ref, atol=atol, rtol=2e-3)
        ref_tok = int(np.argmax(ref))
        assert got_tokens[i] == ref_tok, f"step {i}: {got_tokens[i]} != {ref_tok}"
        seq.append(ref_tok)


# -- prefill/decode parity ----------------------------------------------------


def test_llama_prefill_decode_logits_parity():
    model, params = _tiny_llama()
    _assert_stepwise_parity(model, params, [1, 2, 3, 4, 5], n_steps=6)


def test_gpt2_prefill_decode_logits_parity():
    model, params = _tiny_gpt2()
    _assert_stepwise_parity(model, params, [3, 4, 5, 6], n_steps=5)


def test_qwen3_moe_prefill_decode_logits_parity():
    model, params = _tiny_moe()
    _assert_stepwise_parity(model, params, [7, 8, 9, 10], n_steps=5)


def test_sliding_window_ring_cache_wraparound():
    """Ring layout: capacity == window < prompt + new tokens, so prefill
    already wraps and decode overwrites expired slots; logits must still
    match the full windowed forward at every step."""
    model, params = _tiny_llama(sliding_window=4, num_layers=2)
    # prompt (6) > window (4): prefill wraps; 8 decode steps wrap again
    _assert_stepwise_parity(
        model, params, [1, 2, 3, 4, 5, 6], n_steps=8, window=4, capacity=4
    )


def test_ring_rejects_ragged_wrapping_batch():
    """A ragged batch whose padded prompt wraps the ring would silently
    lose short slots' in-window history — the engine must refuse it."""
    model, params = _tiny_llama(sliding_window=4, num_layers=2)
    from automodel_tpu.auto_model import AutoModel

    auto = AutoModel(model=model, params=params, adapter=None, mesh_ctx=None)
    eng = GenerationEngine(
        auto, GenerationConfig(max_new_tokens=4, greedy=True, pad_to_multiple=1)
    )
    with pytest.raises(ValueError, match="ring"):
        eng.generate_ids([[1, 2, 3, 4, 5, 6], [7, 8]])
    # equal-length wrapping batches and ragged window-fitting ones are fine
    assert eng.generate_ids([[1, 2, 3, 4, 5, 6], [9, 8, 7, 6, 5, 4]])["gen_tokens"] == 8
    assert eng.generate_ids([[1, 2, 3], [7, 8]])["gen_tokens"] == 8


def test_decode_loop_matches_full_forward_greedy_batched():
    """The jitted while_loop engine path on RAGGED slots (different prompt
    lengths in one batch) reproduces per-slot full-forward greedy decode."""
    model, params = _tiny_llama()
    prompts = [[1, 2, 3, 4, 5], [7, 8, 9]]
    from automodel_tpu.auto_model import AutoModel

    auto = AutoModel(model=model, params=params, adapter=None, mesh_ctx=None)
    eng = GenerationEngine(
        auto, GenerationConfig(max_new_tokens=6, greedy=True, pad_to_multiple=1)
    )
    out = eng.generate_ids(prompts)
    for b, prompt in enumerate(prompts):
        seq = list(prompt)
        for _ in range(6):
            seq.append(int(np.argmax(_full_logits(model, params, seq)[-1])))
        assert out["tokens"][b] == seq[len(prompt):]
    assert out["gen_tokens"] == 12
    assert out["prefill_tokens"] == 8
    assert out["ttft_s"] > 0 and out["decode_tps"] > 0
    assert out["cache_bytes"] > 0


def test_stop_token_early_exit():
    model, params = _tiny_llama()
    # discover what greedy emits at step 2, then declare it the stop token
    _, toks = _cached_stepwise_logits(model, params, [1, 2, 3], n_steps=4)
    eos = toks[1]
    apply = lambda p, i, **kw: model(p, i, **kw)
    decode = build_decode_fn(apply, GREEDY, 16, eos_ids=(eos,), pad_id=0)
    prefill = build_prefill_fn(apply)
    cache = kv_cache.init_cache(3, 1, 32, 2, 8, jnp.float32)
    last, cache = prefill(
        params, jnp.asarray([[1, 2, 3]], jnp.int32), jnp.asarray([3], jnp.int32), cache
    )
    first = sample(last, jax.random.key(0), GREEDY)
    res, _ = decode(params, cache, first, jax.random.key(0))
    res = jax.device_get(res)
    # the eos is INCLUDED, everything after is pad, and the while_loop
    # exited early: exactly ONE body iteration ran (first token from
    # prefill, second token = eos), observable via the step counter
    assert res["n_generated"][0] == 2
    assert res["tokens"][0][1] == eos
    assert all(t == 0 for t in res["tokens"][0][2:])
    assert int(res["steps"]) == 1


# -- sampling -----------------------------------------------------------------


def test_sampling_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 3.0, -1.0, 0.5], [2.0, 0.0, 5.0, 1.0]])
    out = sample(logits, jax.random.key(0), SamplingConfig(temperature=0.0))
    assert out.tolist() == [1, 2]


def test_sampling_top_k_restricts_support():
    logits = jnp.asarray([[5.0, 4.0, -10.0, -10.0]] * 64)
    cfg = SamplingConfig(temperature=1.0, top_k=2)
    out = sample(logits, jax.random.key(1), cfg)
    assert set(np.asarray(out).tolist()) <= {0, 1}


def test_sampling_top_p_restricts_support():
    # p(0)≈0.72, p(1)≈0.26: top_p=0.9 keeps {0,1}, cuts {2,3}
    logits = jnp.asarray([[3.0, 2.0, -1.0, -2.0]] * 128)
    cfg = SamplingConfig(temperature=1.0, top_p=0.9)
    out = sample(logits, jax.random.key(2), cfg)
    assert set(np.asarray(out).tolist()) <= {0, 1}


def test_sampling_deterministic_and_key_sensitive():
    logits = jax.random.normal(jax.random.key(3), (4, 32))
    cfg = SamplingConfig(temperature=0.8, top_k=8)
    a = sample(logits, jax.random.key(5), cfg)
    b = sample(logits, jax.random.key(5), cfg)
    c = sample(logits, jax.random.key(6), cfg)
    assert a.tolist() == b.tolist()
    assert a.tolist() != c.tolist()


def test_sampling_config_validation():
    with pytest.raises(ValueError):
        SamplingConfig(top_k=0)
    with pytest.raises(ValueError):
        SamplingConfig(top_p=0.0)
    with pytest.raises(ValueError):
        SamplingConfig(top_p=1.5)


# -- per-host sampling RNG (training/rng.py) ----------------------------------


def test_sampling_key_per_host_streams():
    from automodel_tpu.training.rng import sampling_key

    k_h0 = sampling_key(42, host_index=0)
    k_h1 = sampling_key(42, host_index=1)
    # distinct hosts → distinct streams (multi-host generation must not
    # sample identical tokens on every host)
    assert not np.array_equal(
        jax.random.key_data(k_h0), jax.random.key_data(k_h1)
    )
    # deterministic per (seed, host)
    assert np.array_equal(
        jax.random.key_data(k_h0),
        jax.random.key_data(sampling_key(42, host_index=0)),
    )
    # decode-step fold-in changes the stream, deterministically
    s3 = sampling_key(42, step=3, host_index=0)
    assert not np.array_equal(jax.random.key_data(k_h0), jax.random.key_data(s3))
    assert np.array_equal(
        jax.random.key_data(s3),
        jax.random.key_data(sampling_key(42, step=3, host_index=0)),
    )
    # default host index = jax.process_index() (single-process: 0)
    assert np.array_equal(
        jax.random.key_data(sampling_key(42)), jax.random.key_data(k_h0)
    )
    # accepts an existing key and a traced step (fold_in inside jit)
    jitted = jax.jit(lambda k, i: sampling_key(k, step=i, host_index=0))
    jitted(k_h0, jnp.int32(1))


# -- engine / cache -----------------------------------------------------------


def test_engine_rejects_cacheless_model():
    class NoCacheModel:
        config = None

    class FakeAuto:
        model = NoCacheModel()
        params = None
        mesh_ctx = None
        constrain = staticmethod(lambda x, s: x)

    with pytest.raises(GenerationUnsupported):
        GenerationEngine(FakeAuto(), GenerationConfig())


def test_engine_context_limit():
    model, params = _tiny_llama(max_position_embeddings=16)
    from automodel_tpu.auto_model import AutoModel

    auto = AutoModel(model=model, params=params, adapter=None, mesh_ctx=None)
    eng = GenerationEngine(auto, GenerationConfig(max_new_tokens=20, greedy=True))
    with pytest.raises(ValueError, match="context limit"):
        eng.generate_ids([[1] * 8])


def test_cache_nbytes_and_census_visibility():
    """Cache arrays are ordinary live jax arrays, so the telemetry census
    (jax.live_arrays groups) sees them; nbytes reports the logical size."""
    from automodel_tpu.telemetry.memory import live_array_census

    cache = kv_cache.init_cache(2, 1, 16, 2, 8, jnp.float32)
    expect = 2 * (2 * 1 * 16 * 2 * 8 * 4)  # k+v fp32
    assert cache.nbytes >= expect
    census = live_array_census(top_k=64)
    shapes = {tuple(e["shape"]) for e in census["top"]}
    assert (2, 1, 16, 2, 8) in shapes


def test_engine_on_mesh(devices8):
    """Sharded path: engine over a from_config model on an 8-device CPU
    mesh; cache placement drops non-divisible axes instead of crashing."""
    from automodel_tpu import auto_model
    from automodel_tpu.parallel.mesh import MeshConfig, build_mesh

    ctx = build_mesh(MeshConfig(dp_shard=4, tp=2), devices=devices8)
    hf = {
        "architectures": ["LlamaForCausalLM"], "model_type": "llama",
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "max_position_embeddings": 128,
    }
    auto = auto_model.from_config(
        hf, ctx,
        {"attn": "sdpa", "param_dtype": "float32", "compute_dtype": "float32"},
    )
    eng = GenerationEngine(auto, GenerationConfig(max_new_tokens=4, greedy=True))
    out = eng.generate_ids([[1, 2, 3, 4]] * 4)
    assert len(out["tokens"]) == 4
    assert all(len(t) == 4 for t in out["tokens"])
    # all slots identical prompts → identical greedy completions
    assert out["tokens"][0] == out["tokens"][1]


# -- CLI ----------------------------------------------------------------------


def _tiny_cli_cfg(**gen_over):
    from automodel_tpu.config.loader import ConfigNode

    return ConfigNode(
        {
            "seed": 0,
            "model": {
                "hf_config": {
                    "architectures": ["LlamaForCausalLM"],
                    "model_type": "llama",
                    "vocab_size": 64, "hidden_size": 32,
                    "intermediate_size": 64, "num_hidden_layers": 2,
                    "num_attention_heads": 4, "num_key_value_heads": 2,
                    "head_dim": 8, "max_position_embeddings": 128,
                },
                "backend": {
                    "attn": "sdpa",
                    "param_dtype": "float32",
                    "compute_dtype": "float32",
                },
            },
            "distributed": {"dp_shard": 1, "tp": 1},
            "generation": {"max_new_tokens": 5, "greedy": True, **gen_over},
        }
    )


def test_cli_generate_end_to_end(capsys, monkeypatch, cpu_devices):
    """`automodel_tpu generate` produces text end-to-end on CPU from a tiny
    from-config llama (token-id mode: no tokenizer configured)."""
    monkeypatch.setattr(jax, "devices", lambda *a: cpu_devices[:1])
    cfg = _tiny_cli_cfg()
    cfg.set_by_path("prompt", "1 2 3 4")
    from automodel_tpu.generation.engine import main

    rc = main(cfg)
    captured = capsys.readouterr().out
    assert rc == 0
    assert "completion:" in captured
    completion = [
        l.split("completion:", 1)[1].strip()
        for l in captured.splitlines()
        if l.startswith("completion:")
    ][0]
    assert len(completion.split()) == 5  # 5 greedy tokens as text
    stats = json.loads(
        [l for l in captured.splitlines() if l.startswith("{")][-1]
    )
    assert stats["event"] == "generation"
    assert stats["gen_tokens"] == 5 and stats["ttft_s"] > 0


def test_cli_generate_prompt_ids_and_missing_prompt(capsys, monkeypatch, cpu_devices):
    monkeypatch.setattr(jax, "devices", lambda *a: cpu_devices[:1])
    from automodel_tpu.generation.engine import main

    rc = main(_tiny_cli_cfg(prompt_ids=[[1, 2, 3], [4, 5, 6, 7]]))
    assert rc == 0
    assert capsys.readouterr().out.count("completion:") == 2
    rc = main(_tiny_cli_cfg())
    assert rc == 2  # no prompt anywhere → usage error, not a crash


def test_cli_app_routes_generate(tmp_path, monkeypatch, cpu_devices):
    import yaml

    monkeypatch.setattr(jax, "devices", lambda *a: cpu_devices[:1])
    cfg_path = tmp_path / "gen.yaml"
    cfg_path.write_text(yaml.safe_dump(_tiny_cli_cfg().to_dict()))
    from automodel_tpu.cli.app import main as app_main

    rc = app_main(["generate", "-c", str(cfg_path), "--prompt", "2 3 4"])
    assert rc == 0


# -- benchmark decode leg / report schema -------------------------------------


def test_bench_generation_leg_null_with_reason():
    """A missing generation: section or a cache-less model yields a NULL
    decode leg WITH a recorded reason that validate_bench_result accepts —
    and a bare 0.0 leg still fails validation (the VERDICT r5 rule)."""
    from automodel_tpu.recipes.benchmark import (
        BenchmarkingRecipeForNextTokenPrediction as Bench,
    )
    from automodel_tpu.telemetry.report import validate_bench_result

    rec = Bench.__new__(Bench)
    rec._gen_engine = None
    rec._gen_skip_reason = None
    leg = rec._generation_leg()
    assert leg["gen_decode_tps"] is None
    assert "generation" in leg["gen_failure"]
    assert validate_bench_result({"value": 1.0, **leg}) == []

    rec._gen_skip_reason = "model has no KV-cache decode path"
    leg = rec._generation_leg()
    assert leg["gen_failure"] == "model has no KV-cache decode path"
    assert validate_bench_result({"value": 1.0, **leg}) == []

    # a 0.0-valued decode leg is never a measurement
    bad = {"value": 1.0, "gen_decode_tps": 0.0, "gen_failure": None}
    assert validate_bench_result(bad)
    # and null WITHOUT a reason is flagged
    bad = {"value": 1.0, "gen_decode_tps": None, "gen_failure": None}
    assert validate_bench_result(bad)


def test_report_accepts_generation_keys(tmp_path):
    """ttft_s / decode_tps / gen_* ride the JSONL schema: numeric values
    lint clean, null-without-marker is still flagged."""
    from automodel_tpu.telemetry.report import lint_metrics_jsonl, summarize_metrics

    p = tmp_path / "m.jsonl"
    p.write_text(
        "\n".join(
            [
                json.dumps({"step": 1, "loss": 1.0, "ts": 1.0}),
                json.dumps(
                    {
                        "event": "generation", "step": 1, "ts": 2.0,
                        "ttft_s": 0.5, "decode_tps": 123.4,
                        "gen_tokens": 32, "gen_cache_bytes": 4096,
                        "gen_samples": [{"prompt": "1 2", "completion": "3"}],
                    }
                ),
            ]
        )
        + "\n"
    )
    records, problems = lint_metrics_jsonl(str(p))
    assert problems == []
    summary = summarize_metrics(records)
    assert summary["generation_records"] == 1
    assert summary["decode_tps_mean"] == pytest.approx(123.4)
    # null without marker is still a schema problem
    p.write_text(json.dumps({"step": 1, "ts": 1.0, "decode_tps": None}) + "\n")
    _, problems = lint_metrics_jsonl(str(p))
    assert any("decode_tps" in pr for pr in problems)


# -- train_ft in-training eval generation -------------------------------------


def test_train_ft_logs_generation_at_validation(tmp_path, devices8, monkeypatch):
    monkeypatch.setattr(jax, "devices", lambda *a: devices8)
    from automodel_tpu.config.loader import ConfigNode
    from automodel_tpu.recipes.train_ft import main

    cfg = ConfigNode(
        {
            "seed": 7,
            "model": {
                "hf_config": {
                    "architectures": ["LlamaForCausalLM"],
                    "model_type": "llama",
                    "vocab_size": 128, "hidden_size": 64,
                    "intermediate_size": 128, "num_hidden_layers": 2,
                    "num_attention_heads": 4, "num_key_value_heads": 2,
                    "max_position_embeddings": 128,
                },
                "backend": {
                    "attn": "sdpa",
                    "param_dtype": "float32",
                    "compute_dtype": "float32",
                },
            },
            "distributed": {"dp_shard": 4, "tp": 2},
            "dataset": {
                "_target_": "automodel_tpu.data.sft.MockSFTDataset",
                "vocab_size": 128, "seq_length": 32, "num_samples": 32,
            },
            "dataloader": {"global_batch_size": 8},
            "step_scheduler": {
                "grad_acc_steps": 1, "num_epochs": 1, "max_steps": 4,
                "val_every_steps": 2,
            },
            "optimizer": {"name": "adamw", "lr": 1e-3},
            "loss_fn": {"name": "masked_ce"},
            "logging": {"metrics_path": str(tmp_path / "metrics.jsonl")},
            "generation": {
                "max_new_tokens": 4,
                "greedy": True,
                "prompt_ids": [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12], [13, 14, 15, 16]],
            },
        }
    )
    main(cfg)
    lines = [
        json.loads(l)
        for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    gens = [l for l in lines if l.get("event") == "generation"]
    assert len(gens) >= 2  # val_every_steps=2, max_steps=4
    g = gens[0]
    assert len(g["gen_samples"]) == 4
    assert all(len(s["completion"].split()) == 4 for s in g["gen_samples"])
    assert g["ttft_s"] > 0 and g["decode_tps"] > 0 and g["gen_tokens"] == 16
    # the linter accepts the whole file
    from automodel_tpu.telemetry.report import lint_metrics_jsonl

    _, problems = lint_metrics_jsonl(str(tmp_path / "metrics.jsonl"))
    assert problems == []
