"""Capability probes for environment-dependent tier-1 tests.

Some tests exercise functionality this container's jax/jaxlib/optax build
cannot run (old splash kernel, partial-auto shard_map lowering that emits
GSPMD-rejected PartitionId ops, no multiprocess CPU backend, no
optax.contrib.muon). Letting them FAIL buries real regressions in a wall
of known noise; skipping them wholesale would mask a real regression the
day the environment gains the capability.

The contract here: each probe reproduces the SPECIFIC minimal operation
the gated tests depend on, once per session (cached), and the skip fires
only when that exact probe fails — with the probe's error as the skip
reason. On an environment where the probe passes, the tests run normally
and a regression in the feature fails loudly again.

Usage::

    from capabilities import skip_unless
    @skip_unless("splash_attention")
    def test_flash_kernel_taken_...():
"""

from __future__ import annotations

import functools
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax


@functools.lru_cache(maxsize=None)
def probe(name: str) -> tuple[bool, str]:
    """→ (capability available, reason when not)."""
    return _PROBES[name]()


def skip_unless(name: str):
    """Decorator: skip the test when the named capability probe fails.

    The probe runs LAZILY at test call time (cached per session), not at
    decoration: collection (`--collect-only`, `-k something_else`) must not
    pay for the 2-subprocess multiprocess probe or the pallas-interpret
    splash probe when the gated tests never run."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            ok, reason = probe(name)
            if not ok:
                pytest.skip(f"capability {name!r} unavailable: {reason}")
            return fn(*args, **kwargs)

        return wrapper

    return deco


# ---------------------------------------------------------------------------
# probes
# ---------------------------------------------------------------------------


def _splash_attention() -> tuple[bool, str]:
    """The exact splash invocation the suite's shapes need: GQA, head_dim
    64, seq 128, interpret mode. This build's kernel lacks the ``sinks``
    parameter AND requires head_dim % 128 == 0 — either one breaks every
    flash test, and a future jax upgrade clears both at once."""
    try:
        import jax.numpy as jnp

        from automodel_tpu.ops import attention as attn_mod

        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((1, 128, 2, 64)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 128, 1, 64)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 128, 1, 64)), jnp.float32)
        out = attn_mod._splash_flash(
            q, k, v, None, None, causal=True, scale=0.125,
            logits_soft_cap=None, sliding_window=None,
            block_q=128, block_kv=128, interpret=True,
        )
        assert np.isfinite(np.asarray(out)).all()
    except Exception as e:
        return False, f"{type(e).__name__}: {str(e)[:160]}"
    return True, ""


def _partial_auto_shard_map() -> tuple[bool, str]:
    """The pipeline lowering shape: a shard_map region manual over ``pp``
    with a >1 ``tp`` axis left auto, using ``axis_index`` inside. On 0.4.x
    jaxlib this emits a PartitionId instruction GSPMD refuses
    (UNIMPLEMENTED) — the exact failure of the pp/a2a pipeline tests."""
    try:
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from automodel_tpu.utils.compat import shard_map

        devs = jax.devices("cpu")
        if len(devs) < 4:
            return False, "needs 4 CPU devices"
        mesh = Mesh(np.array(devs[:4]).reshape(2, 2), ("pp", "tp"))

        def body(x):
            return x + jax.lax.axis_index("pp")

        out = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("pp"),), out_specs=P("pp"),
            axis_names={"pp"}, check_vma=False,
        ))(jnp.arange(4.0))
        assert np.asarray(out).shape == (4,)
    except Exception as e:
        return False, f"{type(e).__name__}: {str(e)[:160]}"
    return True, ""


_MP_PROBE_SCRIPT = textwrap.dedent("""\
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = ""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=sys.argv[1], num_processes=2,
        process_id=int(sys.argv[2]),
    )
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    x = jax.device_put(
        jnp.ones((4,), jnp.float32),
        NamedSharding(mesh, P("dp")),
    )
    s = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
    # fetching forces the cross-process computation to actually run
    assert float(jax.device_get(s.addressable_shards[0].data)) == 4.0
    print("MP_PROBE_OK")
""")


def _multiprocess_cpu() -> tuple[bool, str]:
    """Two real processes, one global 4-device CPU mesh, one jitted global
    reduction — the minimal core of test_multiprocess. This build's CPU
    backend answers 'Multiprocess computations aren't implemented'."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_COORDINATOR_ADDRESS",
              "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        env.pop(k, None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _MP_PROBE_SCRIPT,
             f"127.0.0.1:{port}", str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=120)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False, "2-process rendezvous probe timed out"
    for rc, out, err in outs:
        if rc != 0 or "MP_PROBE_OK" not in out:
            tail = err.strip().splitlines()[-1] if err.strip() else f"rc={rc}"
            return False, tail[:160]
    return True, ""


def _muon() -> tuple[bool, str]:
    """optax.contrib.muon: the exact symbol optim/builders.py dispatches to."""
    import optax

    if not hasattr(optax.contrib, "muon"):
        return False, (
            f"optax {getattr(optax, '__version__', '?')} has no contrib.muon"
        )
    return True, ""


_PROBES = {
    "splash_attention": _splash_attention,
    "partial_auto_shard_map": _partial_auto_shard_map,
    "multiprocess_cpu": _multiprocess_cpu,
    "muon": _muon,
}


if __name__ == "__main__":  # manual audit: python tests/capabilities.py
    print(json.dumps(
        {name: {"ok": probe(name)[0], "reason": probe(name)[1]}
         for name in _PROBES},
        indent=2,
    ))
